"""ctypes bridge to the native control-plane core (``csrc/libhvd_core.so``).

Python-side analog of the reference's ``HorovodBasics`` ctypes loader
(``horovod/common/basics.py:22-131``) plus the enqueue path
(``EnqueueTensorAllreduce``, ``operations.cc:803-852``).

Division of labor (inverted from the reference, TPU-style):

- C++ core: background cycle thread, tensor queue, coordinator negotiation
  (TCP across processes), response cache bitvector sync, fusion bin-packing,
  stall detection, timeline.
- Python/XLA: the data plane. The core never sees tensor bytes; each cycle it
  calls back with a fused execution plan (tensor names + op params) and this
  module launches one XLA collective over the registered device arrays.

Env knobs follow the reference catalog (``common/common.h:61-88``,
``operations.cc:403-500``): ``HOROVOD_FUSION_THRESHOLD``,
``HOROVOD_CYCLE_TIME`` (ms), ``HOROVOD_CACHE_CAPACITY``,
``HOROVOD_TIMELINE``, ``HOROVOD_STALL_CHECK_TIME_SECONDS``,
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.observability import metrics as _metrics, trace as _trace
from horovod_tpu.resilience import health as _health

logger = logging.getLogger("horovod_tpu.core")

_serialize_cache: Optional[bool] = None
_serialize_cache_lock = threading.Lock()


def _serialize_collectives() -> bool:
    """Whether collective program launches from the cycle thread must be
    fenced before the next one (CPU backend only — see the call site).
    Built under a lock: first call can race between the cycle thread and
    the main thread (found by hvdlint HVD005)."""
    global _serialize_cache
    with _serialize_cache_lock:
        if _serialize_cache is None:
            _serialize_cache = jax.default_backend() == "cpu"
        return _serialize_cache

_LIB_ENV = "HVD_CORE_LIB"
_DEFAULT_LIB = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "csrc",
    "libhvd_core.so",
)


def _resolve_lib_path(lib_path: str = None) -> str:
    """One resolution rule for the core shared library: explicit arg >
    ``HVD_CORE_LIB`` env > in-tree default."""
    return lib_path or os.environ.get(_LIB_ENV) or _DEFAULT_LIB


def library_available(lib_path: str = None) -> bool:
    """True iff the native core shared library exists on disk (built via
    ``make -C csrc``; used by ``hvdrun --check-build``)."""
    return os.path.exists(_resolve_lib_path(lib_path))


# mirror of csrc/include/hvd/common.h DataType
_DTYPE_TO_TAG = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.uint16): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int64): 5,
    np.dtype(np.float16): 6,
    # bfloat16 handled by name below
    np.dtype(np.float32): 8,
    np.dtype(np.float64): 9,
    np.dtype(np.bool_): 10,
}

REQUEST_ALLREDUCE = 0
REQUEST_ALLGATHER = 1
REQUEST_BROADCAST = 2
REQUEST_JOIN = 3
REQUEST_ADASUM = 4
REQUEST_ALLTOALL = 5
REQUEST_REDUCESCATTER = 6
REQUEST_BARRIER = 7

RESPONSE_ERROR = 8

# mirror of csrc kJoinTensorName (controller.h): JOIN responses carry this
# name so every process can complete its local join() handle
JOIN_TENSOR_NAME = "__hvd_join__"


_dtype_tag_cache: Dict[object, int] = {}


def _dtype_tag(dtype) -> int:
    # memoized: str(dtype) + np.dtype() cost ~30us per call, and the enqueue
    # hot path pays it once per gradient tensor per step
    try:
        return _dtype_tag_cache[dtype]
    except (KeyError, TypeError):
        pass
    tag = 7 if str(dtype) == "bfloat16" else _DTYPE_TO_TAG[np.dtype(dtype)]
    try:
        _dtype_tag_cache[dtype] = tag
    except TypeError:  # unhashable dtype object
        pass
    return tag


def _tag_dtype(tag: int):
    """Inverse of :func:`_dtype_tag` (zero-backfill for joined ranks needs to
    materialize tensors from response metadata alone)."""
    if tag == 7:
        return jnp.bfloat16
    for dt, t in _DTYPE_TO_TAG.items():
        if t == tag:
            return dt
    raise ValueError(f"unknown dtype tag {tag}")


class Response:
    """Decoded execution plan (mirror of hvd::Response)."""

    __slots__ = (
        "response_type",
        "tensor_names",
        "error_message",
        "tensor_sizes",
        "tensor_dtypes",
        "tensor_output_elements",
        "tensor_shapes",
        "tensor_type",
        "root_rank",
        "reduce_op",
        "axis_name",
        "prescale_factor",
        "postscale_factor",
    )


def _parse_response_list(
    buf: bytes,
) -> tuple[List[Response], bool, int, int]:
    """Returns (responses, shutdown, hier_allreduce, hier_allgather); the
    hierarchical pair is the tuned-strategy tail (-1 = never tuned) the
    Python data plane applies at the cycle boundary."""
    off = 0

    def u8():
        nonlocal off
        (v,) = struct.unpack_from("<B", buf, off)
        off += 1
        return v

    def u32():
        nonlocal off
        (v,) = struct.unpack_from("<I", buf, off)
        off += 4
        return v

    def i32():
        nonlocal off
        (v,) = struct.unpack_from("<i", buf, off)
        off += 4
        return v

    def i64():
        nonlocal off
        (v,) = struct.unpack_from("<q", buf, off)
        off += 8
        return v

    def f64():
        nonlocal off
        (v,) = struct.unpack_from("<d", buf, off)
        off += 8
        return v

    def s():
        nonlocal off
        n = u32()
        v = buf[off : off + n].decode()
        off += n
        return v

    shutdown = bool(u8())
    tuned_cycle_ms = f64()
    tuned_fusion = i64()
    tuned_cache = i32()
    # applied inside the C loop, not here
    del tuned_cycle_ms, tuned_fusion, tuned_cache
    out = []
    for _ in range(u32()):
        r = Response()
        r.response_type = i32()
        r.tensor_names = [s() for _ in range(u32())]
        r.error_message = s()
        r.tensor_sizes = [i64() for _ in range(u32())]
        # per-tensor dtype tags: one fused response may mix dtypes (the XLA
        # grouped launch keeps each array's own dtype; no shared buffer)
        r.tensor_dtypes = [i32() for _ in range(u32())]
        # per-tensor total output elements (fusion byte accounting; for
        # allgather tensor_sizes holds per-RANK dim0 blocks instead)
        r.tensor_output_elements = [i64() for _ in range(u32())]
        # per-tensor true shapes (joined-rank cache reconstruction)
        r.tensor_shapes = [
            tuple(i64() for _ in range(u32())) for _ in range(u32())
        ]
        r.tensor_type = i32()
        r.root_rank = i32()
        r.reduce_op = i32()
        r.axis_name = s() or None
        r.prescale_factor = f64()
        r.postscale_factor = f64()
        out.append(r)
    # optional tail (absent on pre-round-5 cores): hierarchical toggles
    hier_ar = hier_ag = -1
    if off + 8 <= len(buf):
        hier_ar = i32()
        hier_ag = i32()
    return out, shutdown, hier_ar, hier_ag


class CoreHandle:
    """Completion handle for a core-negotiated collective."""

    __slots__ = ("name", "event", "result", "error")

    def __init__(self, name: str):
        self.name = name
        self.event = threading.Event()
        self.result = None
        self.error: Optional[str] = None

    def done(self) -> bool:
        return self.event.is_set()

    def wait(self, timeout: Optional[float] = None):
        if not self.event.wait(timeout):
            # attributable from the exception alone: which tensor, and what
            # the process-wide health machine thinks right now
            _health.record_timeout(self.name)
            state = _health.health_state()
            err = TimeoutError(
                f"collective '{self.name}' did not complete within "
                f"{timeout}s (health: {state.name}"
                + (f", {_health.MONITOR.reason()}" if _health.MONITOR.reason()
                   else "")
                + ")"
            )
            err.tensor_name = self.name
            err.health_state = state
            raise err
        if self.error is not None:
            raise RuntimeError(self.error)
        return self.result


# POINTER(c_char), not c_char_p: the payload is binary and c_char_p would
# NUL-truncate it at the first zero byte
_EXEC_CB_T = ctypes.CFUNCTYPE(
    None, ctypes.POINTER(ctypes.c_char), ctypes.c_int,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int
)
_LOG_CB_T = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_char_p)


class _Buckets:
    """Fixed-assignment fusion buckets for one (axis, op) launch space.

    The reference's FusionBufferManager memcpy-packs whatever the cycle
    binned (``common/ops/collective_operations.cc`` MemcpyInFusionBuffer) —
    composition-dependent packing is free when the "program" is a memcpy.
    Under XLA every distinct launch signature is a compilation, so
    arrival-dependent bins (a cycle firing mid-enqueue-burst splits the
    tensor list at a random boundary) recompile forever. These buckets make
    launch signatures arrival-INDEPENDENT: each named tensor is assigned to
    a bucket once, in first-seen order, closing a bucket when it reaches the
    fusion threshold; responses are held until their bucket is complete and
    launched as ONE fused flat-buffer program per bucket (one psum per dtype
    inside — ``ops/collective.py::_eager_fused_allreduce_fn``). Steady-state
    training then replays the same program set every step.

    Held partials cannot wedge or rot: a deadline flusher launches any
    bucket held past ~10 cycle times (>=100 ms) with the members it has,
    and a bucket that deadline-flushes with the same members missing
    several times in a row is REBUILT without them (the missing names lose
    their assignment and re-enter the open bucket if they ever come back),
    so surviving bucket-mates return to completing within a cycle instead
    of paying the deadline every step.
    """

    __slots__ = ("assign", "members", "open_bid", "open_bytes", "pending",
                 "held_since", "flush_strikes", "last_assign", "threshold")

    #: consecutive deadline flushes of a bucket before its absent members
    #: are pruned from the membership (resets on any complete launch)
    PRUNE_AFTER_FLUSHES = 3

    #: a first-seen name arriving this long after the open bucket's last
    #: assignment starts a NEW bucket: registration bursts (a model's
    #: gradient set, ms apart) group, while a later one-off (say, a
    #: per-epoch metric) gets its own bucket and completes immediately
    #: instead of stalling on — and strike-pruning — established mates
    NEW_BUCKET_AFTER_S = 1.0

    def __init__(self, threshold: int):
        self.assign: Dict[str, int] = {}
        self.members: List[List[str]] = []
        self.open_bid = -1
        self.open_bytes = 0
        self.pending: Dict[int, dict] = {}
        self.held_since: Dict[int, float] = {}
        self.flush_strikes: Dict[int, int] = {}
        self.last_assign = 0.0
        self.threshold = threshold

    def bucket_of(self, name: str, nbytes: int) -> int:
        import time as _time

        bid = self.assign.get(name)
        if bid is not None:
            return bid
        now = _time.monotonic()
        if (self.open_bid < 0
                or now - self.last_assign > self.NEW_BUCKET_AFTER_S
                or (self.open_bytes + nbytes > self.threshold
                    and self.open_bytes > 0)):
            self.members.append([])
            self.open_bid = len(self.members) - 1
            self.open_bytes = 0
        bid = self.open_bid
        self.assign[name] = bid
        self.members[bid].append(name)
        self.open_bytes += nbytes
        self.last_assign = now
        return bid

    def add(self, name: str, nbytes: int, item):
        """Route one response entry into its bucket. Returns
        ``(bid, displaced)``: ``displaced`` is a non-empty item list when
        ``name`` was ALREADY held in a partial bucket (a pipelined caller's
        next-step entry arrived before the deadline flushed the previous
        one) — the held generation is drained for immediate launch so its
        handles complete instead of being silently overwritten."""
        import time as _time

        bid = self.bucket_of(name, nbytes)
        displaced = None
        got = self.pending.get(bid)
        if got is not None and name in got:
            displaced = [got[n] for n in self.members[bid] if n in got]
            del self.pending[bid]
            self.held_since.pop(bid, None)
        if bid not in self.pending:
            self.held_since[bid] = _time.monotonic()
        self.pending.setdefault(bid, {})[name] = item
        return bid, displaced

    def take_complete(self, bid: int):
        """The bucket's items in fixed member order, if all present."""
        got = self.pending.get(bid)
        if got is None or len(got) < len(self.members[bid]):
            return None
        del self.pending[bid]
        self.held_since.pop(bid, None)
        self.flush_strikes.pop(bid, None)
        return [got[n] for n in self.members[bid]]

    def take_partials(self, older_than: float = 0.0):
        """Drain held partial buckets (all of them, or only those held
        longer than ``older_than`` seconds — the flush deadline that keeps
        a never-again-enqueued tensor from wedging its bucket-mates).

        A deadline drain (``older_than > 0``) counts a strike against the
        bucket; at :data:`PRUNE_AFTER_FLUSHES` consecutive strikes the
        absent members are pruned from the membership so the survivors go
        back to completing within a cycle (a pruned name that reappears is
        assigned afresh to the open bucket)."""
        import time as _time

        now = _time.monotonic()
        out = []
        for bid in sorted(self.pending):
            if older_than and now - self.held_since.get(bid, 0) < older_than:
                continue
            got = self.pending.pop(bid)
            self.held_since.pop(bid, None)
            if older_than:
                strikes = self.flush_strikes.get(bid, 0) + 1
                if strikes >= self.PRUNE_AFTER_FLUSHES:
                    missing = [n for n in self.members[bid] if n not in got]
                    for n in missing:
                        self.assign.pop(n, None)
                    self.members[bid] = [
                        n for n in self.members[bid] if n in got
                    ]
                    self.flush_strikes.pop(bid, None)
                else:
                    self.flush_strikes[bid] = strikes
            out.append([got[n] for n in self.members[bid] if n in got])
        return out


class NativeCore:
    """Owns the loaded library + pending-tensor registry for this process."""

    def __init__(
        self,
        rank: int = 0,
        size: int = 1,
        coordinator_host: Optional[str] = None,
        coordinator_port: int = 0,
        lib_path: Optional[str] = None,
    ):
        if size > 1 and not coordinator_host:
            raise ValueError(
                "multi-process native core requires a coordinator: set "
                "HVD_CORE_COORD_ADDR (and optionally HVD_CORE_COORD_PORT) or "
                "pass coordinator_host; otherwise each process would "
                "negotiate alone and launch mismatched collectives"
            )
        path = _resolve_lib_path(lib_path)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"native core library not found at {path}; build it with "
                "`make -C csrc` or set HVD_CORE_LIB"
            )
        self._lib = ctypes.CDLL(path)
        self._configure_signatures()
        self._pending: Dict[int, tuple[CoreHandle, object, dict]] = {}
        self._pending_mu = threading.Lock()
        self._next_handle = 0
        self._shutdown_seen = False
        # fixed fusion buckets, one launch space per (axis, op); only the
        # single-process XLA data plane uses them (multi-process exchanges
        # ride the per-name hostlocal path, where launch signatures are not
        # compiled programs). See _Buckets.
        self._buckets: Dict[tuple, _Buckets] = {}
        self._buckets_threshold: Optional[int] = None
        self._buckets_mu = threading.RLock()
        self._flusher_stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None

        # keep callback objects alive for the lib's lifetime
        self._exec_cb = _EXEC_CB_T(self._on_execute)
        self._log_cb = _LOG_CB_T(self._on_log)
        self._lib.hvd_core_set_exec_callback(self._exec_cb)
        self._lib.hvd_core_set_log_callback(self._log_cb)

        #: last globally-agreed cache-hit count folded into metrics (the
        #: lib counter is cumulative; the registry wants deltas per cycle)
        self._cache_hits_seen = 0

        env = os.environ
        timeline = env.get("HOROVOD_TIMELINE", "")
        if timeline:
            # pin the host recorder's ts=0 to the native Timeline's t0
            # (hvd_core_init runs next) so one Perfetto load of the merged
            # file shows both sides on a shared timebase
            _trace.set_epoch()
        rc = self._lib.hvd_core_init(
            rank,
            size,
            (coordinator_host or "").encode(),
            coordinator_port,
            float(env.get("HOROVOD_CYCLE_TIME", "5")),
            int(env.get("HOROVOD_FUSION_THRESHOLD", str(64 * 1024 * 1024))),
            int(env.get("HOROVOD_CACHE_CAPACITY", "1024")),
            float(env.get("HOROVOD_STALL_CHECK_TIME_SECONDS", "60")),
            float(env.get("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "0")),
            timeline.encode(),
        )
        if rc != 0:
            raise RuntimeError("native core initialization failed")

    def _configure_signatures(self):
        lib = self._lib
        lib.hvd_core_init.restype = ctypes.c_int
        lib.hvd_core_init.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_double,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_char_p,
        ]
        lib.hvd_core_enqueue.restype = ctypes.c_int
        lib.hvd_core_enqueue.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_int64,
            ctypes.c_char_p,
        ]
        lib.hvd_core_pending.restype = ctypes.c_int
        lib.hvd_core_initialized.restype = ctypes.c_int
        lib.hvd_core_rank.restype = ctypes.c_int
        lib.hvd_core_size.restype = ctypes.c_int
        lib.hvd_core_cycle_time_ms.restype = ctypes.c_double
        lib.hvd_core_set_cycle_time_ms.argtypes = [ctypes.c_double]
        lib.hvd_core_fusion_threshold.restype = ctypes.c_int64
        lib.hvd_core_set_fusion_threshold.argtypes = [ctypes.c_int64]
        lib.hvd_core_autotune_active.restype = ctypes.c_int
        lib.hvd_core_autotune_samples.restype = ctypes.c_int
        lib.hvd_core_autotune_best_score.restype = ctypes.c_double
        lib.hvd_core_cache_enabled.restype = ctypes.c_int
        lib.hvd_core_set_cache_enabled.argtypes = [ctypes.c_int]
        lib.hvd_core_hier_allreduce.restype = ctypes.c_int
        lib.hvd_core_hier_allgather.restype = ctypes.c_int
        lib.hvd_core_cache_hit_count.restype = ctypes.c_uint64
        lib.hvd_core_set_autotuned_params.argtypes = [
            ctypes.c_double,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
        ]

    # ------------------------------------------------------------- callbacks

    def _on_log(self, level: int, msg: bytes):
        text = msg.decode(errors="replace")
        logger.log(
            {0: logging.DEBUG, 1: logging.INFO, 2: logging.WARNING}.get(
                level, logging.ERROR
            ),
            "%s",
            text,
        )
        if level >= 2 and text.startswith("Stalled collective:"):
            # feed the stall inspector's warning (csrc stall_inspector.h:
            # "Stalled collective: NAME waited Xs; missing ranks: ...")
            # into the health state machine
            try:
                rest = text[len("Stalled collective:"):].strip()
                name, _, tail = rest.partition(" waited ")
                seconds = float(tail.split("s", 1)[0]) if tail else 0.0
                _health.record_stall(name, seconds)
            except Exception:  # the log text must never crash the callback
                _health.record_stall(text)

    def _on_execute(self, payload, length, handles_ptr, n_handles):
        """Runs on the core's background thread (ctypes holds the GIL)."""
        t0 = time.perf_counter()
        try:
            with _trace.span("cycle", "EXECUTE_PLAN"):
                buf = ctypes.string_at(payload, length)
                responses, shutdown, hier_ar, hier_ag = _parse_response_list(
                    buf
                )
                handles = [handles_ptr[i] for i in range(n_handles)]
                if shutdown:
                    self._shutdown_seen = True
                self._apply_hier_toggles(hier_ar, hier_ag)
                # an autotune step that moved the fusion threshold
                # re-buckets: flush held partials under the old assignment
                th = self._lib.hvd_core_fusion_threshold()
                with self._buckets_mu:
                    if self._buckets and th != self._buckets_threshold:
                        self._flush_partial_buckets()
                        self._buckets.clear()
                    self._buckets_threshold = th
                for resp in responses:
                    self._execute_one(resp, handles)
            self._record_cycle(t0, responses)
            if responses:
                # a cycle that launched negotiated plans is progress; empty
                # cycles are not (they keep firing while a tensor stalls,
                # and must not reset the stall strikes)
                _health.beat()
        except Exception:  # never let an exception escape into C
            logger.exception("execution callback failed")
            with self._pending_mu:
                items = list(self._pending.values())
                self._pending.clear()
            for h, _, _ in items:
                h.error = "internal execution failure"
                h.event.set()
            with self._buckets_mu:
                for mgr in self._buckets.values():
                    for items_ in mgr.take_partials():
                        for handle, _, _, _ in items_:
                            handle.error = "internal execution failure"
                            handle.event.set()

    def _record_cycle(self, t0: float, responses: List[Response]):
        """Fold one execute callback into the metrics registry: cycle
        latency (plan receipt -> all launches dispatched), fused-plan
        sizes, and the delta of globally-agreed response-cache hits."""
        if not _metrics.enabled():
            return
        _metrics.histogram(
            "core_cycle_latency_seconds",
            help="execute-callback latency per negotiation cycle",
        ).observe(time.perf_counter() - t0)
        _metrics.counter(
            "core_cycles", help="execute callbacks received"
        ).inc()
        for resp in responses:
            _metrics.counter(
                "core_responses", help="execution-plan responses"
            ).inc()
            if resp.tensor_names:
                _metrics.histogram(
                    "core_fused_plan_tensors",
                    help="tensors per fused execution plan",
                    buckets=_metrics.DEFAULT_SIZE_BUCKETS,
                ).observe(len(resp.tensor_names))
        hits = self._lib.hvd_core_cache_hit_count()
        delta = hits - self._cache_hits_seen
        if delta > 0:
            _metrics.counter(
                "core_cache_hits",
                help="globally-agreed response-cache hits",
            ).inc(delta)
        self._cache_hits_seen = hits

    _hier_applied = (-1, -1)
    _hier_saved = None  # pre-session (_forced, _forced_allgather) pair

    def _apply_hier_toggles(self, hier_ar: int, hier_ag: int):
        """Apply coordinator-tuned hierarchical strategies at the cycle
        boundary (the reference flips its hierarchical ops the same way,
        ``parameter_manager.cc:44-60`` + ``operations.cc:455-469``). -1 =
        never tuned: the user's env/set_hierarchical choice stands. The
        pre-session strategy is saved once and restored by
        :meth:`shutdown` so a dead session's tuned choice does not outlive
        it."""
        if (hier_ar, hier_ag) == self._hier_applied:
            return
        from horovod_tpu.ops import hierarchical

        if self._hier_saved is None and (hier_ar >= 0 or hier_ag >= 0):
            self._hier_saved = (
                hierarchical._forced, hierarchical._forced_allgather,
            )
        if hier_ar >= 0:
            hierarchical.set_hierarchical(bool(hier_ar))
        if hier_ag >= 0:
            hierarchical.set_hierarchical_allgather(bool(hier_ag))
        self._hier_applied = (hier_ar, hier_ag)

    def _flush_partial_buckets(self, older_than: float = 0.0):
        with self._buckets_mu:
            drained = [
                (key, items)
                for key, mgr in self._buckets.items()
                for items in mgr.take_partials(older_than)
            ]
        for key, items in drained:
            if items:
                self._launch_bucket(key, items)

    def _ensure_flusher(self):
        """Deadline flusher: a held partial bucket whose missing members
        never arrive (a tensor that stopped being enqueued) is launched
        with what it has after max(10 cycle times, 100 ms), so bucket-mates
        never wedge; repeated deadline flushes prune the absent members
        (``_Buckets.take_partials``). The deadline sits far above any
        enqueue burst (a burst spans a few cycles) so it can never cut a
        burst into arrival-dependent compositions."""
        if self._flusher is not None:
            return

        def loop():
            while True:
                # comfortably past any enqueue burst (a burst spans a few
                # cycles at short cycle times); only a genuinely abandoned
                # bucket-mate ever waits this long
                deadline = max(
                    10.0 * self._lib.hvd_core_cycle_time_ms() / 1000.0, 0.1)
                # waking at deadline/2 bounds flush latency at 1.5x deadline
                # while keeping lock traffic on _buckets_mu (shared with the
                # cycle thread's execute callback) ~10-20x lower than waking
                # every cycle
                if self._flusher_stop.wait(deadline / 2.0):
                    return
                try:
                    self._flush_partial_buckets(older_than=deadline)
                except Exception:
                    logger.exception("bucket deadline flush failed")

        self._flusher = threading.Thread(
            target=loop, name="hvd-bucket-flusher", daemon=True
        )
        self._flusher.start()

    def _launch_bucket(self, key, items):
        """One fused flat-buffer launch for a (complete or flushed) bucket.
        ``items``: list of (handle, array, pre, post) in bucket order.

        Thread-safe against concurrent calls from the cycle thread and the
        deadline flusher: on CPU backends every collective program goes
        through ``collective._cpu_serialized`` (a process-wide lock held
        across dispatch AND block), and on TPU the per-device stream orders
        launches — so two threads here can never overlap collective
        programs."""
        from horovod_tpu.ops import collective as C

        axis, op_i, rtype = key
        op = C.Adasum if rtype == REQUEST_ADASUM else C.ReduceOp(op_i)
        try:
            arrays = [
                a * pre if pre != 1.0 else a for _, a, pre, _ in items
            ]
            outs = C.grouped_allreduce(arrays, op, axis=axis)
            outs = [
                o * post if post != 1.0 else o
                for o, (_, _, _, post) in zip(outs, items)
            ]
            if _serialize_collectives():
                jax.block_until_ready(outs)  # see _execute_one
            for (handle, _, _, _), out in zip(items, outs):
                handle.result = out
                handle.event.set()
        except Exception as e:
            for handle, _, _, _ in items:
                if not handle.event.is_set():
                    handle.error = str(e)
                    handle.event.set()

    def _execute_one(self, resp: Response, handles: List[int]):
        entries = []
        with self._pending_mu:
            for h in handles:
                entries.append(self._pending.pop(h, None))
        live = [e for e in entries if e is not None]
        if resp.response_type == RESPONSE_ERROR:
            for handle, _, _ in live:
                handle.error = resp.error_message or "collective failed"
                handle.event.set()
            return
        if resp.response_type == REQUEST_JOIN:
            # whole job joined; handle result = last rank to join
            # (reference torch/mpi_ops.py:511-524)
            for handle, _, _ in live:
                handle.result = resp.root_rank
                handle.event.set()
            return
        if (
            resp.response_type in (REQUEST_ALLREDUCE, REQUEST_ADASUM)
            and len(handles) == len(resp.tensor_names)
            and any(e is None for e in entries)
        ):
            # this process join()ed: tensors it never enqueued still need its
            # participation in the collective, with zero contributions
            self._execute_backfilled(resp, entries)
            return
        if not live:
            return
        from horovod_tpu.ops import collective as C

        if (
            resp.response_type in (REQUEST_ALLREDUCE, REQUEST_ADASUM)
            and self._lib.hvd_core_size() == 1
        ):
            # single-process XLA data plane: route through fixed fusion
            # buckets so launch signatures are arrival-independent (see
            # _Buckets). Multi-process exchanges take the per-name hostlocal
            # path below, where composition is not a compiled program.
            self._ensure_flusher()
            ready = []
            with self._buckets_mu:
                touched = set()
                for handle, array, meta in live:
                    op = meta["op"]
                    key = (
                        meta.get("axis"),
                        int(op) if op is not None else resp.reduce_op,
                        resp.response_type,
                    )
                    mgr = self._buckets.get(key)
                    if mgr is None:
                        mgr = self._buckets[key] = _Buckets(
                            self._buckets_threshold
                            or self._lib.hvd_core_fusion_threshold()
                        )
                    nbytes = getattr(array, "nbytes", 0) or int(
                        np.prod(getattr(array, "shape", (1,)) or (1,))) * 4
                    bid, displaced = mgr.add(
                        handle.name, nbytes,
                        (handle, array, resp.prescale_factor,
                         resp.postscale_factor),
                    )
                    if displaced:
                        # previous-generation partial drained by a repeat
                        # name: launch it now so its handles complete
                        ready.append((key, displaced))
                    touched.add((key, bid))
                for key, bid in sorted(
                    touched, key=lambda kb: (str(kb[0]), kb[1])
                ):
                    items = self._buckets[key].take_complete(bid)
                    if items is not None:
                        ready.append((key, items))
            for key, items in ready:
                self._launch_bucket(key, items)
            return

        # The C core fuses by (type, axis, reduce_op, scale factors) and
        # deliberately NOT dtype — the grouped XLA launch keeps each array's
        # own dtype, so one bin may mix fp32/bf16. The axis re-split here is
        # belt-and-braces (the core already fuses within one axis; entries
        # enqueued without an explicit axis resolve it Python-side).
        by_axis: Dict[object, list] = {}
        for entry in live:
            by_axis.setdefault(entry[2].get("axis"), []).append(entry)
        try:
            for axis, group in by_axis.items():
                arrays = [arr for _, arr, _ in group]
                op = group[0][2]["op"]
                pre, post = resp.prescale_factor, resp.postscale_factor
                if pre != 1.0:
                    arrays = [a * pre for a in arrays]
                if resp.response_type in (REQUEST_ALLREDUCE, REQUEST_ADASUM):
                    outs = C.grouped_allreduce(arrays, op, axis=axis)
                elif resp.response_type == REQUEST_ALLGATHER:
                    outs = C.grouped_allgather(arrays, axis=axis)
                elif resp.response_type == REQUEST_BROADCAST:
                    outs = [
                        C.broadcast(a, resp.root_rank, axis=axis)
                        for a in arrays
                    ]
                elif resp.response_type == REQUEST_ALLTOALL:
                    outs = [C.alltoall(a, axis=axis) for a in arrays]
                elif resp.response_type == REQUEST_REDUCESCATTER:
                    outs = [C.reducescatter(a, op, axis=axis) for a in arrays]
                else:  # JOIN / BARRIER
                    outs = arrays
                if post != 1.0:
                    outs = [o * post for o in outs]
                if _serialize_collectives():
                    # XLA:CPU's in-process communicator rendezvouses the
                    # per-device partition threads with NO cross-program
                    # ordering: two collective programs in flight can each
                    # capture part of the pool and abort on rendezvous
                    # timeout. TPU orders launches on the per-device stream,
                    # so only the CPU backend pays this fence.
                    jax.block_until_ready(outs)
                for (handle, _, _), out in zip(group, outs):
                    handle.result = out
                    handle.event.set()
        except Exception as e:
            for handle, _, _ in live:
                if not handle.event.is_set():
                    handle.error = str(e)
                    handle.event.set()

    def _execute_backfilled(self, resp: Response, entries: List):
        """Launch a reduction this joined process only partially (or never)
        enqueued, substituting zeros for the missing tensors (reference
        ``tensor_queue.cc`` ``GetTensorEntriesFromResponse`` zero substitution
        + ``controller.cc:219-307``). Everything is flattened so shapes agree
        across processes regardless of what the live ranks enqueued."""
        from horovod_tpu.ops import collective as C

        live = [e for e in entries if e is not None]
        try:
            # fused responses may mix dtypes; fall back to the single-dtype
            # field when the per-tensor list is absent (older cache entries)
            dtags = resp.tensor_dtypes or [resp.tensor_type] * len(
                resp.tensor_sizes
            )
            metas = [e[2] for e in live]
            # the response echoes the negotiated axis, so a fully-joined
            # process (no live entries) still launches on the right axis
            axis = resp.axis_name
            op = (
                metas[0]["op"]
                if metas and metas[0]["op"] is not None
                else C.ReduceOp(resp.reduce_op)
            )
            if resp.response_type == REQUEST_ADASUM:
                op = C.Adasum
            arrays, shapes = [], []
            for e, size, dtag in zip(entries, resp.tensor_sizes, dtags):
                if e is None:
                    arrays.append(jnp.zeros((int(size),), _tag_dtype(dtag)))
                    shapes.append(None)
                else:
                    a = jnp.asarray(e[1])
                    shapes.append(a.shape)
                    arrays.append(jnp.reshape(a, (-1,)))
            if resp.prescale_factor != 1.0:
                arrays = [a * resp.prescale_factor for a in arrays]
            outs = C.grouped_allreduce(arrays, op, axis=axis)
            if resp.postscale_factor != 1.0:
                outs = [o * resp.postscale_factor for o in outs]
            if _serialize_collectives():
                jax.block_until_ready(outs)  # see _execute_one
            for e, out, shape in zip(entries, outs, shapes):
                if e is not None:
                    handle = e[0]
                    handle.result = jnp.reshape(out, shape)
                    handle.event.set()
        except Exception as e:
            for handle, _, _ in live:
                if not handle.event.is_set():
                    handle.error = str(e)
                    handle.event.set()

    # --------------------------------------------------------------- enqueue

    def enqueue(
        self,
        name: str,
        array,
        request_type: int,
        *,
        op=None,
        root_rank: int = -1,
        prescale: float = 1.0,
        postscale: float = 1.0,
        axis: Optional[str] = None,
    ) -> CoreHandle:
        handle = CoreHandle(name)
        with self._pending_mu:
            hid = self._next_handle
            self._next_handle += 1
            self._pending[hid] = (
                handle,
                array,
                {"op": op, "axis": axis},
            )
        shape = tuple(getattr(array, "shape", ()))
        dims = (ctypes.c_int64 * len(shape))(*shape)
        reduce_op = int(op) if op is not None else 0
        with _trace.span("enqueue", name):
            rc = self._lib.hvd_core_enqueue(
                name.encode(),
                request_type,
                _dtype_tag(getattr(array, "dtype", np.float32)),
                dims,
                len(shape),
                root_rank,
                reduce_op,
                prescale,
                postscale,
                hid,
                (axis or "").encode(),
            )
        if rc == 0 and _metrics.enabled():
            _metrics.counter(
                "core_enqueued_tensors",
                help="tensors enqueued to the native control plane",
            ).inc()
        if rc != 0:
            with self._pending_mu:
                self._pending.pop(hid, None)
            if rc == 1:
                raise ValueError(
                    f"Duplicate tensor name '{name}' in outstanding collective "
                    "(reference DUPLICATE_NAME_ERROR)."
                )
            raise RuntimeError(f"enqueue failed for '{name}' (rc={rc})")
        return handle

    # ----------------------------------------------------------------- misc

    @property
    def cycle_time_ms(self) -> float:
        return self._lib.hvd_core_cycle_time_ms()

    @cycle_time_ms.setter
    def cycle_time_ms(self, ms: float):
        self._lib.hvd_core_set_cycle_time_ms(ms)

    @property
    def fusion_threshold(self) -> int:
        return self._lib.hvd_core_fusion_threshold()

    @fusion_threshold.setter
    def fusion_threshold(self, b: int):
        self._lib.hvd_core_set_fusion_threshold(b)

    def pending_count(self) -> int:
        return self._lib.hvd_core_pending()

    # autotuner status (reference ParameterManager observability)
    def autotune_active(self) -> bool:
        return bool(self._lib.hvd_core_autotune_active())

    def autotune_samples(self) -> int:
        return self._lib.hvd_core_autotune_samples()

    def autotune_best_score(self) -> float:
        return self._lib.hvd_core_autotune_best_score()

    def cache_enabled(self) -> bool:
        """Response-cache toggle as currently applied (autotuned)."""
        return bool(self._lib.hvd_core_cache_enabled())

    def cache_hit_count(self) -> int:
        """Globally-agreed cache hits this process proposed (steady-state
        observability; a rejoin that renegotiates stalls this counter)."""
        return self._lib.hvd_core_cache_hit_count()

    def hier_allreduce(self) -> int:
        """Hierarchical-allreduce strategy as applied job-wide this cycle
        (-1 = never tuned, 0 = flat, 1 = hierarchical)."""
        return self._lib.hvd_core_hier_allreduce()

    def hier_allgather(self) -> int:
        return self._lib.hvd_core_hier_allgather()

    def set_autotuned_params(self, *, cycle_ms: float = 0.0,
                             fusion_bytes: int = -1, cache_enabled: int = -1,
                             hier_allreduce: int = -1,
                             hier_allgather: int = -1):
        """Coordinator-side manual retune: the values ride the NEXT cycle's
        broadcast and every rank applies them at the same cycle boundary —
        the collectively-safe way to flip strategies mid-run (the autotuner
        uses the identical path). No-op on non-coordinator ranks."""
        self._lib.hvd_core_set_autotuned_params(
            cycle_ms, fusion_bytes, cache_enabled, hier_allreduce,
            hier_allgather,
        )

    def set_cache_enabled(self, enabled: bool):
        """Single-process/local override only. Multi-process jobs must
        toggle via the coordinator broadcast (autotune) so all ranks switch
        at the same cycle boundary — a one-rank toggle desynchronizes the
        cache-hit bitvector AND (the disabled rank proposes no hits) and
        stalls negotiation until the stall inspector kills the job."""
        if self._lib.hvd_core_size() > 1:
            raise RuntimeError(
                "set_cache_enabled is single-process only; in multi-process "
                "jobs the cache toggle must ride the coordinator broadcast "
                "(HOROVOD_AUTOTUNE) so every rank switches at the same "
                "cycle boundary"
            )
        self._lib.hvd_core_set_cache_enabled(1 if enabled else 0)

    def shutdown(self):
        self._flusher_stop.set()
        self._lib.hvd_core_shutdown()
        if self._hier_saved is not None:
            # restore the pre-session strategy this session's tuned
            # broadcast overrode (see _apply_hier_toggles)
            from horovod_tpu.ops import hierarchical

            hierarchical.set_hierarchical(self._hier_saved[0])
            hierarchical.set_hierarchical_allgather(self._hier_saved[1])
            self._hier_saved = None
            self._hier_applied = (-1, -1)
        # cycle thread is joined now; any bucket still held partial can
        # never complete — fail its waiters instead of hanging them
        with self._buckets_mu:
            drained = [
                items
                for mgr in self._buckets.values()
                for items in mgr.take_partials()
            ]
        for items in drained:
            for handle, _, _, _ in items:
                if not handle.event.is_set():
                    handle.error = "core shut down with queued tensors"
                    handle.event.set()
