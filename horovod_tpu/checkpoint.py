"""Checkpoint/resume helpers.

In the reference, checkpointing is a documented *pattern*, not a subsystem
(SURVEY §5.4): rank 0 writes (``examples/pytorch_imagenet_resnet50.py``,
``examples/tensorflow2_keras_mnist.py``), and on restart everyone restores
rank 0's state via ``broadcast_parameters``/``broadcast_optimizer_state``
(reference ``torch/__init__.py:451-648``, ``tensorflow/__init__.py:126-152``).

This module packages that pattern TPU-natively:

- :func:`save` — rank-0-only write (every process holds the replicated
  global state, so one writer suffices); ``.npz`` + pickled treedef, with
  an atomic rename so a died-mid-write checkpoint is never loaded.
- :func:`restore` — read on every process + broadcast from root so all ranks
  resume bit-identically even if their local filesystems disagree.
- :func:`latest_step` — resume discovery, skipping corrupt or incomplete
  step directories (missing treedef, truncated ``.npz``) so resume falls
  back to the newest *valid* checkpoint instead of dying on the newest
  directory (the resilience layer's emergency-checkpoint path depends on
  this: a host killed mid-``rename`` must not poison the restart).
- :func:`attach_data_state` / :func:`detach_data_state` — the input
  pipeline's ``(epoch, step)`` cursors ride the payload
  (``"data_cursor"``): ``resilience.run``'s periodic and emergency
  checkpoints attach the registered loaders' cursors, and resume restores
  them, so a kill/resume mid-epoch reproduces the exact remaining sample
  stream (``docs/data.md``).
"""

from __future__ import annotations

import logging
import os
import pickle
import re
import tempfile
import zipfile
from typing import Any, Optional

import jax
import numpy as np

from horovod_tpu import basics
from horovod_tpu.ops import collective as C

_STEP_RE = re.compile(r"^step_(\d+)$")

logger = logging.getLogger("horovod_tpu.checkpoint")


def _is_writer() -> bool:
    """Process rank 0 writes. Before ``hvd.init`` the launcher's identity
    env decides (a launched-but-uninitialized worker must not multi-write a
    shared directory); a standalone uninitialized process is its own
    rank 0 (``resilience.run`` checkpoints without ``hvd.init``)."""
    if basics.is_initialized():
        return basics.process_rank() == 0
    return int(
        os.environ.get(
            "HVD_PROCESS_ID", os.environ.get("HOROVOD_RANK", "0")
        )
    ) == 0


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step}")


def attach_data_state(payload: dict, cursors: Optional[dict] = None
                      ) -> dict:
    """Return `payload` with the input plane's loader cursors attached
    under ``"data_cursor"`` (verbatim `cursors` when given — the elastic
    driver passes its COMMITTED cursors, which may trail the live ones;
    otherwise the live registry export). Unchanged when no loader is
    registered, so states that never touch the data plane round-trip
    byte-identically."""
    if cursors is None:
        from horovod_tpu.data import sampler as _sampler

        cursors = _sampler.export_state()
    if not cursors:
        return payload
    out = dict(payload)
    out["data_cursor"] = cursors
    return out


def detach_data_state(payload: Any) -> Any:
    """Restore any ``"data_cursor"`` riding `payload` into the loader
    registry (pending until the loader registers, on a cold restart) and
    return the payload without it. Non-dict payloads pass through."""
    if not isinstance(payload, dict) or "data_cursor" not in payload:
        return payload
    payload = dict(payload)
    cursors = payload.pop("data_cursor")
    try:
        from horovod_tpu.data import sampler as _sampler

        # npz round-trips ints as 0-d arrays: coerce back
        _sampler.restore_state({
            str(name): {str(k): int(v) for k, v in cur.items()}
            for name, cur in dict(cursors).items()
        })
    except Exception:
        logger.warning("data-cursor restore failed", exc_info=True)
    return payload


def save(directory: str, step: int, state: Any, *, force: bool = False,
         fence: bool = True) -> str:
    """Write `state` (any pytree of arrays + picklable leaves) for `step`.

    Only process rank 0 writes (reference pattern: ``hvd.rank() == 0`` guard
    in every example script). With ``fence=True`` (default) all ranks then
    synchronize on the writer's status — a writer-side failure raises on
    EVERY rank instead of leaving the others hung in a barrier; that makes
    the call collective, so every rank must reach it. ``fence=False`` skips
    the status broadcast for callers that cannot assume their peers are
    still participating (the emergency checkpoint on an asymmetric
    preemption: one SIGTERMed rank must not block on ranks that are still
    training). The write is atomic either way: staged into a temp dir,
    renamed into place."""
    path = _step_dir(directory, step)
    err: Optional[BaseException] = None
    if _is_writer():
        try:
            _write_checkpoint(directory, path, step, state, force)
        except BaseException as e:
            err = e
    err_msg = repr(err) if err is not None else None
    status = _sync_status(err_msg) if fence else err_msg
    if err is not None:
        raise err
    if status is not None:
        raise RuntimeError(f"checkpoint write failed on rank 0: {status}")
    return path


def _write_checkpoint(directory, path, step, state, force):
    if os.path.exists(path):
        if not force:
            raise FileExistsError(f"checkpoint already exists: {path}")
        import shutil

        shutil.rmtree(path)
    os.makedirs(directory, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=directory, prefix=f".tmp_step_{step}_")
    try:
        leaves, treedef = jax.tree_util.tree_flatten(state)
        arrays = {}
        meta = []
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
                arrays[f"a{i}"] = np.asarray(leaf)
                meta.append(("array", f"a{i}"))
            else:
                meta.append(("obj", leaf))
        np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
        with open(os.path.join(tmp, "tree.pkl"), "wb") as f:
            pickle.dump({"treedef": treedef, "meta": meta}, f)
        os.rename(tmp, path)
    except BaseException:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise


def restore(directory: str, step: Optional[int] = None, *,
            broadcast_root: int = 0) -> Any:
    """Load a checkpoint on `broadcast_root` ONLY and broadcast it, so every
    rank resumes from identical state even when the checkpoint exists solely
    on the root host's filesystem (the reference's restore-then-broadcast
    pattern, ``tensorflow/__init__.py:126-152`` docstring)."""
    multi = basics.is_initialized() and basics.process_size() > 1
    i_am_root = not multi or basics.process_rank() == broadcast_root

    d = None
    arrays = None
    err = None
    if i_am_root:
        try:
            if step is None:
                step = latest_step(directory)
                if step is None:
                    raise FileNotFoundError(
                        f"no checkpoints under {directory}"
                    )
            path = _step_dir(directory, step)
            with open(os.path.join(path, "tree.pkl"), "rb") as f:
                d = pickle.load(f)
            arrays = np.load(os.path.join(path, "arrays.npz"))
        except BaseException as e:
            err = e
    if not multi:
        if err is not None:
            raise err
    else:
        # ship structure + object leaves + array specs from root; non-root
        # never touches its local filesystem
        if i_am_root and err is None:
            spec = {
                "treedef": d["treedef"],
                "meta": d["meta"],
                "shapes": {
                    k: (arrays[k].shape, arrays[k].dtype.str)
                    for kind, k in d["meta"]
                    if kind == "array"
                },
            }
            payload = {"ok": True, "spec": spec}
        elif i_am_root:
            payload = {"ok": False, "error": repr(err)}
        else:
            payload = None
        payload = C.broadcast_object(payload, broadcast_root)
        if not payload["ok"]:
            if err is not None:
                raise err
            raise RuntimeError(
                f"checkpoint restore failed on rank {broadcast_root}: "
                f"{payload['error']}"
            )
        d = payload["spec"]

    leaves = []
    for kind, v in d["meta"]:
        if kind != "array":
            leaves.append(v)
            continue
        if multi:
            shape, dtype = d["shapes"][v]
            local = (
                np.asarray(arrays[v])
                if i_am_root
                else np.zeros(shape, np.dtype(dtype))
            )
            leaves.append(np.asarray(C.broadcast(local, broadcast_root)))
        else:
            leaves.append(arrays[v])
    return jax.tree_util.tree_unflatten(d["treedef"], leaves)


def consolidate_opt_state(opt_state, params, *, to_size: Optional[int] = None,
                          axis=None):
    """Re-pack a restored ZeRO-1 sharded optimizer state for the current
    world size.

    :func:`save` already persists the *consolidated* view of sharded
    moments — every ``[N, shard]`` leaf is materialized as the full global
    array on the writer (rank 0 owns the addressable single-controller
    view), so the checkpoint is world-size-portable by construction. What
    changes across world sizes is the *packing*: the flat per-dtype buffers
    are padded to a multiple of N, so an 8-way state does not reshape onto
    4 ranks. Call this after :func:`restore` with the freshly restored
    ``params`` (the same tree the state was initialized from)::

        state = checkpoint.restore(ckpt_dir)
        opt_state = checkpoint.consolidate_opt_state(
            state["opt_state"], state["params"])

    Delegates to :func:`horovod_tpu.optim.reshard_optimizer_state`; leaves
    without a rank axis (replicated/non-sharded state) pass through, so the
    call is safe on any optimizer state.

    ZeRO-3: when ``params`` is a :class:`horovod_tpu.optim.FsdpParams`
    (param-sharded training), pass it here *as restored* — the re-pack
    derives shapes/dtypes and the bucket plan from its metadata, so a
    param-sharded state moves across world sizes the same way (re-shard
    the params themselves with
    :func:`horovod_tpu.optim.fsdp_reshard_params` first, then consolidate
    the state against the re-packed tree)."""
    from horovod_tpu.optim import reshard_optimizer_state

    return reshard_optimizer_state(
        opt_state, params, to_size=to_size, axis=axis)


def state_nbytes(state: Any) -> int:
    """Raw array bytes a full checkpoint of `state` persists (the ``.npz``
    member payload, before zip framing) — the denominator of the serving
    layer's delta-vs-full-checkpoint wire comparison
    (``bench.py --publish-ab``, ``scaling_projection.publish_bytes``)."""
    return sum(
        np.asarray(leaf).nbytes
        for leaf in jax.tree_util.tree_leaves(state)
        if isinstance(leaf, (jax.Array, np.ndarray, np.generic))
    )


def is_valid_checkpoint(path: str) -> bool:
    """Is `path` a loadable ``step_N`` directory? ``tree.pkl`` must
    unpickle, every ``.npz`` member must read back intact (zipfile
    CRC-checks each member as it is decompressed — a truncated write,
    power loss after the atomic rename, or a torn copy fails here
    instead of at ``restore``), and no float leaf may carry NaN/Inf — a
    checkpoint of numerically poisoned state is skipped exactly like a
    corrupt one, so resume/rollback can never land training (or the
    weight publisher's consolidation) back on poison. One full read of
    the archive covers both checks; a resume pays roughly one extra read
    of the newest checkpoint — the price of never dying on (or resuming
    into) a bad one. States that legitimately carry non-finite leaves
    (additive ``-inf`` mask buffers, ``inf`` best-loss trackers) opt out
    of the poison sweep with ``HOROVOD_CHECKPOINT_FINITE_CHECK=0`` —
    CRC validation still runs."""
    return _checkpoint_invalid_reason(path) is None


def _checkpoint_invalid_reason(path: str) -> Optional[str]:
    """None when `path` is a valid checkpoint; otherwise ``"corrupt"``
    (unreadable/torn/CRC failure) or ``"nonfinite"`` (intact archive
    rejected only by the finiteness sweep) — resume uses the distinction
    to tell a config problem (a model that legitimately stores non-finite
    leaves) apart from real corruption."""
    import zlib

    from horovod_tpu.resilience.numerics import (
        array_finite, checkpoint_finite_check_enabled)

    finite_check = checkpoint_finite_check_enabled()

    tree = os.path.join(path, "tree.pkl")
    npz = os.path.join(path, "arrays.npz")
    if not (os.path.isfile(tree) and os.path.isfile(npz)):
        return "corrupt"
    try:
        with open(tree, "rb") as f:
            pickle.load(f)
    except Exception:
        return "corrupt"
    if not finite_check:
        # no poison sweep wanted: stream every member through zipfile's
        # decompress-time CRC check instead of np.load-materializing the
        # arrays — validation of a multi-GB checkpoint must not allocate
        # its largest member on a small-RAM resume host
        try:
            with zipfile.ZipFile(npz) as zf:
                for name in zf.namelist():
                    with zf.open(name) as m:
                        while m.read(1 << 20):
                            pass
        except (zipfile.BadZipFile, zlib.error, EOFError, OSError,
                ValueError) as e:
            logger.warning("checkpoint %s is corrupt (%s)", path, e)
            return "corrupt"
        return None
    try:
        with np.load(npz) as z:
            for k in z.files:
                try:
                    a = z[k]  # full member read: zipfile verifies the CRC
                except (zipfile.BadZipFile, zlib.error, EOFError,
                        OSError) as e:
                    logger.warning(
                        "checkpoint %s member %s is corrupt (%s)",
                        path, k, e)
                    return "corrupt"
                except Exception as e:
                    # a member np.load cannot materialize (object dtype
                    # under allow_pickle=False, exotic custom dtypes)
                    # must still be CRC-verified — stream the raw member
                    # (zipfile checks the CRC as it decompresses), the
                    # coverage the old testzip() gave — without failing
                    # an intact archive over the dtype itself
                    logger.debug(
                        "finiteness sweep skipped member %s: %s", k, e)
                    try:
                        zf = getattr(z, "zip", None)
                        if zf is not None:
                            name = (
                                k if k in zf.namelist() else k + ".npy"
                            )
                            with zf.open(name) as m:
                                while m.read(1 << 20):
                                    pass
                    except Exception as e2:
                        logger.warning(
                            "checkpoint %s member %s is corrupt (%s)",
                            path, k, e2)
                        return "corrupt"
                    continue
                if not array_finite(a):
                    logger.warning(
                        "checkpoint %s carries non-finite values in %s; "
                        "treating it as invalid", path, k,
                    )
                    return "nonfinite"
    except (zipfile.BadZipFile, OSError, ValueError, EOFError):
        return "corrupt"
    return None


def _step_listing(directory: str) -> list:
    if not os.path.isdir(directory):
        return []
    return sorted(
        int(m.group(1))
        for name in os.listdir(directory)
        if (m := _STEP_RE.match(name))
    )


def _warn_all_nonfinite(directory: str, reasons: list) -> None:
    """Every candidate was rejected and ONLY by the finiteness sweep: that
    is a config problem (a model that legitimately stores non-finite
    leaves invalidates every checkpoint it writes), not corruption — and
    silently restarting from step 0 would be how the operator finds out.
    Name the escape hatch loudly."""
    if reasons and all(r == "nonfinite" for r in reasons):
        logger.error(
            "ALL %d checkpoints under %s were rejected solely by the "
            "non-finite sweep — resume will restart from scratch. If your "
            "model legitimately stores non-finite leaves (additive -inf "
            "mask buffers, inf best-loss trackers), set "
            "HOROVOD_CHECKPOINT_FINITE_CHECK=0.",
            len(reasons), directory,
        )


def valid_steps(directory: str) -> list:
    """Ascending step numbers of the *valid* checkpoints under `directory`;
    corrupt/incomplete ones are skipped with a warning. Validates every
    directory — use :func:`latest_step` when only the newest is needed."""
    steps = []
    reasons = []
    for s in _step_listing(directory):
        reason = _checkpoint_invalid_reason(_step_dir(directory, s))
        if reason is None:
            steps.append(s)
        else:
            reasons.append(reason)
            logger.warning(
                "skipping %s checkpoint %s",
                reason, _step_dir(directory, s),
            )
    if not steps:
        _warn_all_nonfinite(directory, reasons)
    return steps


def latest_step(directory: str) -> Optional[int]:
    """Highest step with a complete, *valid* checkpoint (corrupt or
    incomplete ``step_N`` directories are skipped, so resume falls back to
    the newest checkpoint that can actually be loaded). Validation walks
    newest-first and stops at the first loadable one — a directory of N
    retained checkpoints costs one CRC sweep, not N."""
    reasons = []
    for s in reversed(_step_listing(directory)):
        reason = _checkpoint_invalid_reason(_step_dir(directory, s))
        if reason is None:
            return s
        reasons.append(reason)
        logger.warning(
            "skipping %s checkpoint %s",
            reason, _step_dir(directory, s),
        )
    _warn_all_nonfinite(directory, reasons)
    return None


def _sync_status(err_msg: Optional[str]) -> Optional[str]:
    """Cross-process fence carrying the writer's status: every rank learns
    whether the write succeeded (None) or failed (the error string), so a
    writer-side exception can never strand the other ranks in a barrier."""
    if basics.is_initialized() and basics.process_size() > 1:
        return C.broadcast_object(err_msg, 0)
    return err_msg


class CheckpointManager:
    """Keep-last-N rotation over :func:`save`/:func:`restore` — the
    convenience layer orbax users expect, on the rank-0-writer pattern.

    ``save(..., asynchronous=True)`` overlaps the disk write with training
    (the orbax async pattern, idiomatic on TPU where the step loop should
    never stall on host IO): the device→host snapshot is taken synchronously
    — the state the checkpoint captures is the state at the call — and the
    serialize+write+rotate runs on a background thread. The writer's status
    is fenced across ranks in :meth:`wait_until_finished`, which the next
    ``save``/``restore`` calls implicitly; like every fence here it is a
    collective when ``process_size() > 1``, so all ranks must reach it in
    the same order (never call it from only one rank)."""

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._pending = None  # (thread | None, [err]) of the in-flight save

    def save(self, step: int, state: Any, *, force: bool = False,
             asynchronous: bool = False) -> str:
        self.wait_until_finished()
        if not asynchronous:
            path = save(self.directory, step, state, force=force)
            self._rotate()
            return path

        path = _step_dir(self.directory, step)
        thread = None
        err_box: list = []
        if _is_writer():
            # Snapshot errors go through err_box + the fence too (never raise
            # before _pending is set): a writer that raised here while the
            # other ranks queued up for the status broadcast would strand
            # them in the collective.
            try:
                # np.array copies: a np.asarray view would let later in-place
                # mutation of host arrays leak into the background write
                snapshot = jax.tree_util.tree_map(
                    lambda x: np.array(x)
                    if isinstance(x, (jax.Array, np.ndarray, np.generic))
                    else x,
                    state,
                )
            except BaseException as e:
                err_box.append(e)
            else:

                def _work():
                    try:
                        _write_checkpoint(
                            self.directory, path, step, snapshot, force)
                        self._rotate()
                    except BaseException as e:  # surfaced at the fence
                        err_box.append(e)

                import threading

                # non-daemon: an interpreter exiting without an explicit
                # wait_until_finished still joins the thread, so the final
                # checkpoint's atomic rename lands instead of being lost
                thread = threading.Thread(
                    target=_work, name=f"hvd-ckpt-save-{step}", daemon=False)
                thread.start()
        self._pending = (thread, err_box)
        return path

    def wait_until_finished(self) -> None:
        """Block until the in-flight async save (if any) completes, then
        fence the writer's status across ranks — a writer-side failure
        raises on every rank. Collective when ``process_size() > 1``."""
        if self._pending is None:
            return
        thread, err_box = self._pending
        self._pending = None
        if thread is not None:
            thread.join()
        err = err_box[0] if err_box else None
        status = _sync_status(repr(err) if err is not None else None)
        if err is not None:
            raise err
        if status is not None:
            raise RuntimeError(f"checkpoint write failed on rank 0: {status}")

    def _rotate(self) -> None:
        if not (_is_writer() and self.max_to_keep):
            return
        import shutil

        steps = sorted(
            s
            for name in os.listdir(self.directory)
            if (m := _STEP_RE.match(name)) and (s := int(m.group(1))) >= 0
        )
        for old in steps[: -self.max_to_keep]:
            shutil.rmtree(_step_dir(self.directory, old), ignore_errors=True)

    def restore(self, step: Optional[int] = None) -> Any:
        self.wait_until_finished()
        return restore(self.directory, step)

    def latest_step(self) -> Optional[int]:
        self.wait_until_finished()
        return latest_step(self.directory)
