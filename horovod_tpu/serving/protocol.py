"""Wire protocol for streaming weight publication (training → serving).

A published **generation** is one pytree of weights, either a full-precision
**keyframe** or an int8-compressed **delta** against the previous
generation's *reconstruction*. The delta chain is self-correcting the same
way error feedback is: the publisher tracks exactly what a subscriber that
decoded every generation holds (``decode(encode(...))`` of its own payload),
and each delta is measured against THAT — quantization error never
accumulates across generations, it is re-measured and re-folded into the
next delta. A subscriber's tree is therefore *bit-identical* to the
publisher's reconstruction, and within one blockwise-int8 quantization error
of the trainer's true weights.

On the KV the layout is commit-last:

- ``/<scope>/chunks/<gen>/<i>`` — the payload split into bounded blobs;
- ``/<scope>/manifest/<gen>`` — JSON: generation, step, kind, base/keyframe
  generation, per-chunk CRC32s, payload CRC, elastic generation fence;
- ``/<scope>/head`` — the newest *committed* generation, written only after
  every chunk and the manifest have landed.

A reader that starts from ``head`` can never observe a torn generation: a
publisher that died mid-publish left chunks (and possibly a manifest)
nobody points at, and its successor overwrites them. Integrity inside a
generation is CRC-checked per chunk and over the whole payload;
:class:`ChainError` is the subscriber's single resync trigger (gap, GC'd
manifest, CRC mismatch, base mismatch).

Quantization reuses the PR-5 wire format verbatim
(:func:`horovod_tpu.compression.quantize_blockwise`: one bf16 max-abs scale
per 256-element block); leaves below the compressor's
``min_quant_elems`` floor — and non-float leaves — ride raw, exactly like
the collective wire.
"""

from __future__ import annotations

import json
import pickle
import zlib
from typing import Any, List, Optional, Tuple

import numpy as np

from horovod_tpu.compression import (
    INT8_BLOCK,
    Int8Compressor,
    _pad_to_block,
    dequantize_blockwise,
    quantize_blockwise,
)

FORMAT_VERSION = 1

#: payload chunk size on the KV (env ``HOROVOD_PUBLISH_CHUNK_BYTES``)
DEFAULT_CHUNK_BYTES = 1 << 20


class ChainError(RuntimeError):
    """The generation chain cannot be applied from here: a manifest is
    missing or GC-tombstoned, a chunk failed its CRC, or a delta's base
    does not match the subscriber's generation. The remedy is always the
    same — resync from the chain's keyframe."""


def head_key(scope: str) -> str:
    return f"/{scope}/head"


def manifest_key(scope: str, generation: int) -> str:
    return f"/{scope}/manifest/{generation}"


def chunk_key(scope: str, generation: int, index: int) -> str:
    return f"/{scope}/chunks/{generation}/{index}"


def crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _is_array(x) -> bool:
    return isinstance(x, np.ndarray) or (
        hasattr(x, "shape") and hasattr(x, "dtype") and hasattr(x, "__array__")
    )


def encode(tree: Any, base: Optional[Any] = None, *,
           block: int = INT8_BLOCK) -> Tuple[bytes, dict]:
    """Serialize `tree` as one payload blob.

    With ``base=None`` this is a keyframe: every array leaf rides raw at
    full precision. With a `base` (the previous generation's
    reconstruction, same treedef) each quantizable leaf's *delta* is
    blockwise-int8 quantized; small/integer/16-bit leaves ride their raw
    delta, non-array leaves ride as objects. Returns ``(payload, info)``
    where ``info["wire_bytes"]`` counts the array bytes on the wire (the
    number the analytic byte model reproduces) and ``info["kind"]`` is
    ``"key"``/``"delta"``."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    base_leaves = None
    if base is not None:
        base_leaves, base_def = jax.tree_util.tree_flatten(base)
        if base_def != treedef:
            raise ValueError(
                "delta base treedef does not match the published tree")
    records: List[tuple] = []
    wire = 0
    finite = True  # float leaves of the ENCODED tree (keyframe path)
    for i, leaf in enumerate(leaves):
        if not _is_array(leaf):
            records.append(("obj", leaf))
            continue
        arr = np.asarray(leaf)
        if base_leaves is None:
            if arr.dtype.kind == "f" and finite \
                    and not np.isfinite(arr).all():
                finite = False
            records.append(("raw", arr))
            wire += arr.nbytes
            continue
        if arr.dtype.kind not in "fiu":
            # bool masks and other non-subtractable dtypes ride as the
            # full value inside a delta (numpy bool subtraction raises)
            records.append(("full", arr))
            wire += arr.nbytes
            continue
        delta = arr - np.asarray(base_leaves[i], dtype=arr.dtype)
        if Int8Compressor.quantizes(arr.shape, arr.dtype):
            import jax.numpy as jnp

            flat = _pad_to_block(jnp.asarray(delta).reshape(-1), block)
            q, scales = quantize_blockwise(flat, block)
            q_np, s_np = np.asarray(q), np.asarray(scales)
            records.append(("q", q_np, s_np, arr.shape, arr.dtype.str))
            wire += q_np.size + s_np.size * 2  # int8 values + bf16 scales
        else:
            records.append(("raw", delta))
            wire += delta.nbytes
    kind = "key" if base is None else "delta"
    payload = pickle.dumps({
        "v": FORMAT_VERSION,
        "kind": kind,
        "block": block,
        "treedef": treedef,
        "records": records,
    })
    info = {"kind": kind, "wire_bytes": wire, "leaves": len(leaves)}
    if base is None:
        # keyframe: the encoded tree IS the publisher's reconstruction,
        # and np.asarray already paid the device→host transfer above —
        # record its finiteness here so the publisher's poisoned-base
        # check never forces a SECOND full-model transfer
        info["finite"] = finite
    return payload, info


def decode(payload: bytes, base: Optional[Any] = None, *,
           device: bool = False) -> Any:
    """Inverse of :func:`encode`: payload (+ `base` for deltas) → pytree of
    owned numpy leaves. The publisher runs this over its own payload to
    track the subscriber view, so both sides are bit-identical by
    construction.

    ``device=True`` is the serving engine's ingest mode: blockwise-int8
    delta leaves land **in their quantized wire form** — the int8 buffer
    and bf16 scales go straight onto the device and the
    dequant-accumulate runs there (XLA fuses it into one pass), so a
    generation update never round-trips a full f32 materialization
    through host memory. Leaves come back as jax arrays; the values are
    bit-identical to the host path (both are IEEE f32 elementwise ops —
    pinned by test), so the publisher-reconstruction contract is
    unchanged."""
    import jax

    d = pickle.loads(payload)
    if d.get("v") != FORMAT_VERSION:
        raise ChainError(f"unknown payload format version {d.get('v')!r}")
    block = d["block"]
    base_leaves = None
    if d["kind"] == "delta":
        if base is None:
            raise ChainError("delta payload decoded without a base tree")
        base_leaves = jax.tree_util.tree_flatten(base)[0]
    leaves = []
    for i, rec in enumerate(d["records"]):
        tag = rec[0]
        if tag == "obj":
            leaves.append(rec[1])
            continue
        if tag == "full":  # full value inside a delta: no base addition
            leaves.append(_own(rec[1], device))
            continue
        if tag == "raw":
            val = rec[1]
            if device:
                import jax.numpy as jnp

                val = jnp.asarray(val)
        else:  # ("q", q, scales, shape, dtype)
            import jax.numpy as jnp

            _, q, scales, shape, dtype = rec
            size = int(np.prod(shape, dtype=np.int64))
            flat = dequantize_blockwise(
                jnp.asarray(q), jnp.asarray(scales), np.dtype(dtype), block)
            val = flat[:size].reshape(shape)
            if not device:
                val = np.asarray(val)
        if base_leaves is not None:
            if device:
                import jax.numpy as jnp

                val = jnp.asarray(base_leaves[i], val.dtype) + val
            else:
                val = np.asarray(base_leaves[i], dtype=val.dtype) + val
        leaves.append(_own(val, device))
    return jax.tree_util.tree_unflatten(d["treedef"], leaves)


def _own(val, device: bool):
    """An owned leaf: numpy copy on the host path, device array on the
    engine path (jnp.asarray of a jax array is a no-op — already owned)."""
    if device:
        import jax.numpy as jnp

        return jnp.asarray(val)
    return np.array(val)


def split_chunks(payload: bytes, chunk_bytes: int) -> List[bytes]:
    if chunk_bytes < 1:
        raise ValueError(f"chunk_bytes must be >= 1, got {chunk_bytes}")
    return [
        payload[i:i + chunk_bytes]
        for i in range(0, len(payload), chunk_bytes)
    ] or [b""]


def build_manifest(*, generation: int, step: int, kind: str,
                   keyframe: int, chunks: List[bytes], payload: bytes,
                   wire_bytes: int, elastic_generation: Optional[int],
                   published_at: float, chain: str = "") -> bytes:
    """The commit record for one generation (JSON; values a subscriber in
    another language could parse — only the payload itself is pickled).

    `chain` is the publisher instance's unique token: generation numbers
    alone cannot identify a delta's base across a trainer restart (a fresh
    publisher re-uses numbers over the same KV), so a delta is applicable
    only when BOTH its base generation and its chain match what the
    subscriber holds — any chain change is a resync trigger."""
    return json.dumps({
        "version": FORMAT_VERSION,
        "generation": generation,
        "step": step,
        "kind": kind,
        "base": generation - 1 if kind == "delta" else None,
        "keyframe": keyframe,
        "chain": chain,
        "chunks": len(chunks),
        "chunk_crc": [crc(c) for c in chunks],
        "payload_bytes": len(payload),
        "payload_crc": crc(payload),
        "wire_bytes": wire_bytes,
        "elastic_generation": elastic_generation,
        "time": published_at,
    }).encode()


def parse_manifest(blob: bytes) -> dict:
    """Parse AND structurally validate a manifest. Every malformed shape
    raises :class:`ChainError` here — the subscriber's poll() catches only
    that, so a corrupt manifest (the one record no CRC protects) must
    never escape as a TypeError/KeyError and crash a serving process."""
    try:
        m = json.loads(blob)
    except ValueError as e:
        raise ChainError(f"unparseable manifest: {e}") from None
    if not isinstance(m, dict):
        raise ChainError(f"manifest is {type(m).__name__}, not an object")
    if m.get("version") != FORMAT_VERSION:
        raise ChainError(f"unknown manifest version {m.get('version')!r}")
    try:
        gen = int(m["generation"])
        kf = int(m["keyframe"])
        chunks = int(m["chunks"])
        crcs = m["chunk_crc"]
        int(m["payload_bytes"])
        int(m["payload_crc"])
        kind = m["kind"]
    except (KeyError, TypeError, ValueError) as e:
        raise ChainError(f"malformed manifest field: {e!r}") from None
    if kind not in ("key", "delta"):
        raise ChainError(f"unknown manifest kind {kind!r}")
    if not (1 <= kf <= gen):
        raise ChainError(f"keyframe {kf} outside [1, {gen}]")
    if kind == "delta" and m.get("base") != gen - 1:
        raise ChainError(f"delta {gen} with base {m.get('base')!r}")
    if chunks < 1 or not isinstance(crcs, list) or len(crcs) != chunks:
        raise ChainError(
            f"chunk table mismatch: {chunks} chunks, "
            f"{len(crcs) if isinstance(crcs, list) else 'no'} CRCs")
    return m
