"""Polling weight subscriber — the serving-fleet side of the handoff.

:class:`WeightSubscriber` follows the commit-last protocol from the reader
end: read ``head``, then the manifests/chunks it points at, CRC-checking
everything. The failure philosophy is **degrade, don't crash**: any problem
applying the chain (a GC'd manifest, a CRC mismatch, a gap after a KV
restart that lost its disk) triggers one resync from the chain's keyframe;
if even that fails the subscriber keeps serving generation ``G−k`` and
reports the lag through the staleness watermark instead of raising. A
trainer that is preempted, resizing, or simply gone makes ``poll()`` return
None forever while ``staleness_seconds()`` grows — the serving process
decides when stale is too stale (``stale()`` /
``HOROVOD_SERVING_STALE_AFTER``).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Optional

from horovod_tpu.observability import flight as _flight
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.resilience import chaos as _chaos, retry as _retry
from horovod_tpu.serving import protocol
from horovod_tpu.serving.protocol import ChainError

__all__ = ["WeightSubscriber", "subscribe_weights"]

logger = logging.getLogger("horovod_tpu.serving")

STALE_AFTER_ENV = "HOROVOD_SERVING_STALE_AFTER"


class WeightSubscriber:
    """Incrementally reconstruct published weights from a KV store.

    `store` is the same duck type the publisher takes: a
    :class:`~horovod_tpu.run.rendezvous.KVStoreServer` (direct) or
    :class:`~horovod_tpu.run.rendezvous.KVStoreClient` (HTTP). All KV reads
    ride the shared retry policy (``HOROVOD_RETRY_SUBSCRIBE_*``).

    - :meth:`poll` — apply everything new; returns the fresh tree when the
      generation advanced, else None. Never raises for trainer-side
      conditions (no publication yet, torn nothing — that cannot happen —
      GC'd history, KV briefly down).
    - :meth:`weights` / :attr:`generation` / :attr:`step` — what is being
      served right now.
    - :meth:`lag` / :meth:`staleness_seconds` / :meth:`stale` — the
      staleness contract: serve G−k, report how far behind.
    """

    def __init__(self, store, *, scope: str = "serving",
                 retry_policy: Optional[_retry.RetryPolicy] = None,
                 stale_after: Optional[float] = None,
                 device: bool = False):
        #: device=True is the inference engine's ingest mode: payloads
        #: decode with ``protocol.decode(..., device=True)`` — int8 delta
        #: leaves land on the accelerator in wire form and the
        #: dequant-accumulate runs there, so the served tree is
        #: device-resident with no host f32 round-trip (values stay
        #: bit-identical to the host path)
        self._device = bool(device)
        self._store = store
        self._scope = scope.strip("/")
        self._retry = retry_policy or _retry.policy_from_env(
            "subscribe", max_attempts=4, base_delay=0.05, max_delay=1.0,
            deadline=30.0,
        )
        self._stale_after = float(
            stale_after
            if stale_after is not None
            else os.environ.get(STALE_AFTER_ENV, "0")
        )
        self._tree: Any = None
        self._generation = 0
        self._step: Optional[int] = None
        self._published_at: Optional[float] = None
        self._head_seen = 0
        self._chain: Optional[str] = None  # publisher token of the applied chain
        self._applies = 0  # commits ever; poll() reports progress from it

    # ----------------------------------------------------------- properties

    @property
    def generation(self) -> int:
        """The generation currently being served (0 = nothing yet)."""
        return self._generation

    @property
    def step(self) -> Optional[int]:
        """The trainer step of the served generation."""
        return self._step

    def weights(self) -> Any:
        """The currently served weight tree (None before the first
        successful poll)."""
        return self._tree

    def lag(self) -> int:
        """Generations between the last observed head and what is served —
        0 when caught up."""
        return max(0, self._head_seen - self._generation)

    def staleness_seconds(self) -> Optional[float]:
        """Wall-clock age of the served generation (publisher timestamp →
        now), or None before the first apply. Grows without bound while
        the trainer is preempted/resizing — that is the signal."""
        if self._published_at is None:
            return None
        return max(0.0, time.time() - self._published_at)

    def stale(self) -> bool:
        """True when the served weights are older than the configured
        watermark (``stale_after`` / ``HOROVOD_SERVING_STALE_AFTER``;
        0 disables). A serving process uses this to degrade gracefully —
        shed traffic, report lag — instead of crashing."""
        if self._stale_after <= 0:
            return False
        age = self.staleness_seconds()
        return age is None or age > self._stale_after

    # ---------------------------------------------------------------- polls

    def poll(self) -> Optional[Any]:
        """Apply every generation published since the last poll.

        Returns the new weight tree when the served generation advanced,
        None otherwise (nothing new, nothing published yet, or recovery
        exhausted — in which case the old tree keeps being served and the
        staleness watermark reports the gap)."""
        _chaos.maybe_delay("subscriber_stall")
        head = self._read_head()
        if head is None:
            self._record_gauges()
            return None
        self._head_seen = head
        if head == self._generation:
            self._record_gauges()
            return None
        # progress = "did a generation COMMIT during this poll", not "did
        # we reach head": applying 2 of 3 pending generations and then
        # failing must still hand the caller the newest applied tree —
        # returning None there would leave the serving process on old
        # weights while the staleness watermark (set by the commit)
        # reports fresh, the exact stale-marked-fresh state the
        # acceptance criteria forbid.
        applies0 = self._applies
        try:
            if head < self._generation:
                # a new publisher re-rooted LOWER than what we serve (the
                # KV lost its disk and the trainer restarted): our chain is
                # dead — resync onto the new one rather than ignore it
                # forever
                logger.warning(
                    "head went backward (%d < %d): new publisher chain; "
                    "resyncing", head, self._generation)
                self._resync(head, reason="chain")
            elif self._tree is None:
                self._resync(head, reason="fresh")
            else:
                try:
                    for g in range(self._generation + 1, head + 1):
                        self._apply_generation(g)
                except ChainError as e:
                    logger.warning(
                        "weight chain broken at generation %d (%s); "
                        "resyncing from keyframe", self._generation + 1, e)
                    self._resync(head, reason="chain")
        except ChainError as e:
            # even the keyframe path failed: keep serving what we have
            logger.warning(
                "weight resync to generation %d failed (%s); still "
                "serving generation %d", head, e, self._generation)
            if _metrics.enabled():
                _metrics.counter(
                    "serving_subscribe_errors",
                    help="polls that could neither advance nor resync",
                ).inc()
        self._record_gauges()
        return self._tree if self._applies > applies0 else None

    def wait_for_generation(self, generation: int, *,
                            timeout: float = 30.0,
                            interval: float = 0.05) -> Any:
        """Poll until at least `generation` is served; returns the tree.
        Raises ``TimeoutError`` past `timeout` — a bootstrap convenience
        for serving processes that need SOME weights before taking
        traffic."""
        deadline = time.monotonic() + timeout
        while True:
            self.poll()
            if self._generation >= generation:
                return self._tree
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no weight generation >= {generation} within "
                    f"{timeout}s (serving {self._generation}, head "
                    f"{self._head_seen})"
                )
            time.sleep(interval)

    # ------------------------------------------------------------- internals

    def _read_head(self) -> Optional[int]:
        blob = self._get(protocol.head_key(self._scope))
        if blob is None:
            return None
        try:
            return int(blob)
        except ValueError:
            return None

    def _get(self, key: str) -> Optional[bytes]:
        """Retry-wrapped KV read; a tombstoned key (410/DeadRankError over
        HTTP) reads as missing — for this protocol both mean "resync"."""
        from horovod_tpu.run.rendezvous import (
            DeadRankError,
            TRANSIENT_KV_ERRORS,
        )

        try:
            return self._retry.call(
                self._store.get, key, retriable=TRANSIENT_KV_ERRORS)
        except DeadRankError:
            return None
        except _retry.RetryError:
            return None

    def _fetch(self, generation: int) -> tuple:
        """(manifest, payload) for one generation, fully CRC-verified.
        Raises :class:`ChainError` on anything short of that."""
        blob = self._get(protocol.manifest_key(self._scope, generation))
        if blob is None:
            raise ChainError(f"manifest {generation} missing or GC'd")
        m = protocol.parse_manifest(blob)
        parts = []
        for i in range(m["chunks"]):
            c = self._get(protocol.chunk_key(self._scope, generation, i))
            if c is None:
                raise ChainError(f"chunk {generation}/{i} missing")
            if protocol.crc(c) != m["chunk_crc"][i]:
                raise ChainError(f"chunk {generation}/{i} CRC mismatch")
            parts.append(c)
        payload = b"".join(parts)
        if len(payload) != m["payload_bytes"] \
                or protocol.crc(payload) != m["payload_crc"]:
            raise ChainError(f"payload {generation} CRC mismatch")
        return m, payload

    def _apply_generation(self, generation: int) -> None:
        m, payload = self._fetch(generation)
        if m["kind"] == "delta":
            if m["base"] != self._generation or self._tree is None:
                raise ChainError(
                    f"delta {generation} bases on {m['base']}, serving "
                    f"{self._generation}"
                )
            if m.get("chain") != self._chain:
                # a restarted publisher re-used this generation NUMBER but
                # its base is a different tree — applying would silently
                # corrupt the served weights
                raise ChainError(
                    f"delta {generation} belongs to publisher chain "
                    f"{m.get('chain')!r}, serving {self._chain!r}"
                )
            tree = protocol.decode(payload, self._tree, device=self._device)
        else:
            tree = protocol.decode(payload, device=self._device)
        self._commit(m, payload, tree)

    def _resync(self, head: int, *, reason: str) -> bool:
        """Rebuild from the chain's keyframe: head's manifest names it;
        replay keyframe..head fresh. Raises :class:`ChainError` when the
        keyframe chain itself is unreadable."""
        if _metrics.enabled():
            _metrics.counter(
                "serving_subscribe_resyncs",
                help="keyframe resyncs by trigger",
                reason=reason,
            ).inc()
        m_head, payload_head = self._fetch(head)
        kf = int(m_head["keyframe"])
        if kf == head:
            if m_head["kind"] != "key":
                raise ChainError(f"generation {head} claims to be its own "
                                 "keyframe but is a delta")
            self._commit(m_head, payload_head,
                         protocol.decode(payload_head, device=self._device))
            return True
        tree = None
        committed = None
        chain = None
        for g in range(kf, head + 1):
            m, payload = (m_head, payload_head) if g == head \
                else self._fetch(g)
            if g == kf:
                if m["kind"] != "key":
                    raise ChainError(f"keyframe {kf} is not a keyframe")
                chain = m.get("chain")
                tree = protocol.decode(payload, device=self._device)
            else:
                if m["kind"] != "delta" or m["base"] != g - 1 \
                        or m.get("chain") != chain:
                    raise ChainError(
                        f"generation {g} does not chain from {g - 1}")
                tree = protocol.decode(payload, tree, device=self._device)
            committed = (m, payload, tree)
        m, payload, tree = committed
        self._commit(m, payload, tree)
        return True

    def _commit(self, manifest: dict, payload: bytes, tree: Any) -> None:
        self._tree = tree
        self._generation = int(manifest["generation"])
        self._step = manifest.get("step")
        self._published_at = manifest.get("time")
        self._chain = manifest.get("chain")
        self._applies += 1
        # flight ring: which generation this process was serving is the
        # first question a serving post-mortem asks
        _flight.record(
            "serve", what="subscribe", generation=self._generation,
            payload=manifest.get("kind"),
        )
        if _metrics.enabled():
            _metrics.counter(
                "serving_subscribe_bytes",
                help="payload bytes fetched and applied",
            ).inc(len(payload))

    def _record_gauges(self) -> None:
        if not _metrics.enabled():
            return
        _metrics.gauge(
            "serving_subscribe_generation",
            help="weight generation currently served",
        ).set(self._generation)
        _metrics.gauge(
            "serving_subscribe_lag_generations",
            help="generations between the observed head and what is served",
        ).set(self.lag())
        age = self.staleness_seconds()
        if age is not None:
            _metrics.gauge(
                "serving_subscribe_staleness_seconds",
                help="wall-clock age of the served generation",
            ).set(age)


def subscribe_weights(addr: Optional[str] = None,
                      port: Optional[int] = None, *,
                      store=None, scope: str = "serving",
                      secret: Optional[str] = None,
                      **kwargs) -> WeightSubscriber:
    """Open a weight subscription — the ``hvd.subscribe_weights()`` entry
    point a serving process polls::

        sub = hvd.subscribe_weights("10.0.0.1", 7799)
        while True:
            fresh = sub.poll()
            if fresh is not None:
                model.load(fresh)
            if sub.stale():
                health.degrade(f"weights {sub.staleness_seconds():.0f}s old")
            time.sleep(poll_interval)

    Pass ``addr``/``port`` (and optionally `secret`, default
    ``HVD_RUN_SECRET``) for the launcher's KV server over HTTP, or
    ``store=`` for an in-process :class:`KVStoreServer`. Remaining kwargs
    reach :class:`WeightSubscriber`."""
    if store is None:
        if addr is None or port is None:
            raise ValueError(
                "subscribe_weights needs addr+port (HTTP) or store= "
                "(in-process)")
        from horovod_tpu.run.rendezvous import KVStoreClient

        store = KVStoreClient(addr, int(port), secret=secret)
    elif addr is not None or port is not None:
        raise ValueError("pass either addr/port or store=, not both")
    return WeightSubscriber(store, scope=scope, **kwargs)
