"""horovod_tpu.serving: fault-tolerant streaming weight publication.

The training → serving handoff (ROADMAP item 4): a live training run
publishes consolidated weights to the rendezvous KV as generation-numbered,
CRC-checksummed, commit-last manifests — full keyframes every K generations
with blockwise-int8 deltas in between — and any number of serving processes
reconstruct them with :func:`subscribe_weights`, surviving publisher
crashes, KV restarts (the server's write-ahead log), elastic resizes (the
generation fence), and their own lag (keyframe resync + the staleness
watermark). See ``docs/serving.md`` for the protocol and contracts.
"""

from horovod_tpu.serving.protocol import ChainError  # noqa: F401
from horovod_tpu.serving.publisher import (  # noqa: F401
    PublishAborted,
    PublishError,
    PublishRejected,
    WeightPublisher,
    active_publishers,
    flush_on_preempt,
)
from horovod_tpu.serving.subscriber import (  # noqa: F401
    WeightSubscriber,
    subscribe_weights,
)

__all__ = [
    "ChainError",
    "PublishAborted",
    "PublishError",
    "PublishRejected",
    "WeightPublisher",
    "WeightSubscriber",
    "active_publishers",
    "flush_on_preempt",
    "subscribe_weights",
]
