"""horovod_tpu.serving: the training → serving plane (ROADMAP item 4).

Two halves:

- **Weight handoff** — a live training run publishes consolidated weights
  to the rendezvous KV as generation-numbered, CRC-checksummed,
  commit-last manifests (full keyframes every K generations with
  blockwise-int8 deltas in between), and any number of serving processes
  reconstruct them with :func:`subscribe_weights`, surviving publisher
  crashes, KV restarts (the server's write-ahead log), elastic resizes
  (the generation fence), and their own lag (keyframe resync + the
  staleness watermark).
- **Inference plane** — :class:`InferenceEngine` serves the subscribed
  weights under continuous batching on a paged KV cache, and
  :class:`GenerationRollout` canaries each new generation on a traffic
  slice, gating promotion on the numerics verdicts plus live serving
  metrics with auto-rollback to G−1. :class:`FleetRouter` fronts N
  replicas (each with its own subscriber) with health-aware routing,
  hedged retries, replica failover, and :class:`FleetRollout` — the
  canary state machine promoted to one fleet-wide, KV-coordinated
  decision (ISSUE 17).

See ``docs/serving.md`` for the protocol and contracts.

The engine modules import lazily (they pull in jax/flax); the handoff
surface stays importable from collection-time contexts like before.
"""

from horovod_tpu.serving.protocol import ChainError  # noqa: F401
from horovod_tpu.serving.publisher import (  # noqa: F401
    PublishAborted,
    PublishError,
    PublishRejected,
    WeightPublisher,
    active_publishers,
    flush_on_preempt,
)
from horovod_tpu.serving.subscriber import (  # noqa: F401
    WeightSubscriber,
    subscribe_weights,
)

__all__ = [
    "ChainError",
    "ContinuousBatchingScheduler",
    "FleetReplica",
    "FleetRequest",
    "FleetRollout",
    "FleetRouter",
    "FleetSaturated",
    "GenerationRollout",
    "InferenceEngine",
    "PublishAborted",
    "PublishError",
    "PublishRejected",
    "QueueFull",
    "Request",
    "WeightPublisher",
    "WeightSubscriber",
    "active_publishers",
    "flush_on_preempt",
    "note_subscriber_health",
    "subscribe_weights",
]

_LAZY = {
    "InferenceEngine": ("horovod_tpu.serving.engine", "InferenceEngine"),
    "note_subscriber_health": (
        "horovod_tpu.serving.engine", "note_subscriber_health"),
    "GenerationRollout": (
        "horovod_tpu.serving.rollout", "GenerationRollout"),
    "ContinuousBatchingScheduler": (
        "horovod_tpu.serving.scheduler", "ContinuousBatchingScheduler"),
    "Request": ("horovod_tpu.serving.scheduler", "Request"),
    "QueueFull": ("horovod_tpu.serving.scheduler", "QueueFull"),
    "FleetReplica": ("horovod_tpu.serving.fleet", "FleetReplica"),
    "FleetRequest": ("horovod_tpu.serving.fleet", "FleetRequest"),
    "FleetRollout": ("horovod_tpu.serving.fleet", "FleetRollout"),
    "FleetRouter": ("horovod_tpu.serving.fleet", "FleetRouter"),
    "FleetSaturated": ("horovod_tpu.serving.fleet", "FleetSaturated"),
}


def __getattr__(name):
    # engine/rollout import flax+jax; keep `import horovod_tpu.serving`
    # as light as the handoff-only days (the PR-8 lazy-package pattern)
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(entry[0])
    val = getattr(mod, entry[1])
    globals()[name] = val
    return val
