"""Generation-numbered weight publication onto the rendezvous KV.

:class:`WeightPublisher` is the trainer side of the training → serving
handoff: every N steps rank 0 consolidates the weights (the
``training.host_snapshot`` discipline — an owned host copy that survives a
mesh teardown), encodes a keyframe or an int8 delta
(:mod:`horovod_tpu.serving.protocol`), and publishes it commit-last: chunks
first, manifest next, the ``head`` pointer only after everything landed. A
publisher crash at ANY point mid-publish leaves the previous head intact —
subscribers can never observe a torn generation.

Failure handling is layered the same way the rest of the stack is:

- transient KV failures (and the ``publish_fail`` chaos charge, which fires
  partway through the chunk upload) retry under the shared
  :class:`~horovod_tpu.resilience.retry.RetryPolicy`
  (``HOROVOD_RETRY_PUBLISH_*``), overwriting the partial upload;
- an elastic resize mid-publish trips the **generation fence**
  (``fence_fn``): the in-flight generation is deleted and
  :class:`PublishAborted` raised — the elastic driver republishes from the
  post-resize consolidated state;
- superseded generations are GC'd back to the newest keyframe (manifests
  tombstoned so a lagging subscriber sees "GC'd", not "never existed"),
  bounding KV memory.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Any, Callable, Optional

import numpy as np

from horovod_tpu.observability import flight as _flight
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.resilience import chaos as _chaos, retry as _retry
from horovod_tpu.serving import protocol

__all__ = [
    "PublishError",
    "PublishAborted",
    "PublishRejected",
    "WeightPublisher",
    "active_publishers",
    "flush_on_preempt",
]

logger = logging.getLogger("horovod_tpu.serving")

KEYFRAME_EVERY_ENV = "HOROVOD_PUBLISH_KEYFRAME_EVERY"
CHUNK_BYTES_ENV = "HOROVOD_PUBLISH_CHUNK_BYTES"
PUBLISH_EVERY_ENV = "HOROVOD_PUBLISH_EVERY"


class PublishError(RuntimeError):
    """A publication failed after exhausting its retry budget; the head
    still points at the last committed generation."""


class PublishAborted(PublishError):
    """The elastic generation fence changed mid-publish: the in-flight
    generation was deleted, nothing was committed. Republish from the
    post-resize consolidated state."""


class PublishRejected(PublishError):
    """The numerics gate refused the generation BEFORE any byte went to
    the KV: the consolidated tree is non-finite, the trainer's most
    recent guarded steps were BAD, or a corrupting-rank quarantine is
    pending. The head still points at the last healthy commit —
    subscribers keep serving it under the staleness contract
    (``serving_publish_rejected{reason=}`` counts the refusal).
    Disable with ``HOROVOD_PUBLISH_NUMERICS_GATE=0``."""

    def __init__(self, reason: str, generation: int):
        super().__init__(
            f"weight generation {generation} rejected by the numerics "
            f"gate (reason={reason})"
        )
        self.reason = reason
        self.generation = generation


#: publishers that registered for the preemption-drain final flush
_ACTIVE: "weakref.WeakSet[WeightPublisher]" = weakref.WeakSet()
_ACTIVE_LOCK = threading.Lock()


def active_publishers() -> list:
    with _ACTIVE_LOCK:
        return list(_ACTIVE)


def flush_on_preempt(state: Any, step: int, budget_s: float) -> int:
    """Best-effort final publication from every registered publisher —
    the SIGTERM-drain hook (:mod:`horovod_tpu.resilience.loop`).
    `budget_s` bounds the WHOLE flush pass, not each publisher — a hanging
    KV must not multiply the drain overrun by the publisher count and eat
    the emergency checkpoint's grace window. Never raises; returns how
    many publishers flushed."""
    deadline = time.monotonic() + budget_s
    n = 0
    for pub in active_publishers():
        remaining = deadline - time.monotonic()
        if remaining <= 0.05:
            logger.warning(
                "preemption flush budget exhausted; skipping remaining "
                "publisher(s)")
            break
        if pub.flush(state, step, budget_s=remaining):
            n += 1
    return n


def _tree_finite(tree: Any) -> bool:
    """True when every float leaf of `tree` is finite — the delta-base
    health check (host numpy; the reconstruction is already host-side)."""
    import jax

    for leaf in jax.tree_util.tree_flatten(tree)[0]:
        if not hasattr(leaf, "dtype"):
            continue
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            return False
    return True


def default_extract(state: Any) -> Any:
    """The weight tree a serving fleet consumes from a training state: the
    ``params`` entry of a loop-state dict, else the state itself."""
    if isinstance(state, dict) and "params" in state:
        return state["params"]
    return state


class WeightPublisher:
    """Publish consolidated weights to a KV store as numbered generations.

    `store` is anything with the rendezvous surface (``put``/``get``/
    ``delete``): a :class:`~horovod_tpu.run.rendezvous.KVStoreServer`
    (direct, single-controller) or a
    :class:`~horovod_tpu.run.rendezvous.KVStoreClient` (the launcher's KV
    over HTTP).

    - `keyframe_every`: publish a full-precision keyframe every K
      generations (env ``HOROVOD_PUBLISH_KEYFRAME_EVERY``, default 8);
      deltas in between ride the blockwise-int8 wire.
    - `publish_every`: step cadence for :meth:`maybe_publish` (env
      ``HOROVOD_PUBLISH_EVERY``; 0 = only explicit :meth:`publish` calls).
    - `fence_fn`: returns the current elastic generation; a change between
      publish start and commit aborts the in-flight generation
      (:class:`PublishAborted`). :class:`horovod_tpu.resilience.elastic.
      ElasticRun` wires this to its coordinator automatically.
    - `extract`: training state → weight tree (default: ``state["params"]``
      for dicts, else the state).
    - `register`: join the process-wide registry the preemption drain
      flushes (:func:`flush_on_preempt`).
    """

    def __init__(self, store, *, scope: str = "serving",
                 keyframe_every: Optional[int] = None,
                 chunk_bytes: Optional[int] = None,
                 publish_every: Optional[int] = None,
                 retry_policy: Optional[_retry.RetryPolicy] = None,
                 fence_fn: Optional[Callable[[], int]] = None,
                 extract: Optional[Callable[[Any], Any]] = None,
                 register: bool = True):
        self._store = store
        self._scope = scope.strip("/")
        self._keyframe_every = max(1, int(
            keyframe_every
            if keyframe_every is not None
            else os.environ.get(KEYFRAME_EVERY_ENV, "8")
        ))
        self._chunk_bytes = int(
            chunk_bytes
            if chunk_bytes is not None
            else os.environ.get(
                CHUNK_BYTES_ENV, str(protocol.DEFAULT_CHUNK_BYTES))
        )
        self._publish_every = int(
            publish_every
            if publish_every is not None
            else os.environ.get(PUBLISH_EVERY_ENV, "0")
        )
        self._retry = retry_policy or _retry.policy_from_env(
            "publish", max_attempts=4, base_delay=0.05, max_delay=1.0,
            deadline=30.0,
        )
        self.fence_fn = fence_fn
        self._extract = extract or default_extract
        self._generation = 0
        self._keyframe_gen = 0
        self._gc_floor = 1  # lowest generation still on the KV
        self._chunk_counts: dict = {}  # generation -> chunks written
        self._recon: Any = None  # the subscriber view (decode of own wire)
        self._recon_finite = True  # False → next delta re-roots (keyframe)
        self._last_step = -1
        #: unique per publisher INSTANCE: a restarted trainer's fresh
        #: publisher writes a new chain, so a surviving subscriber can
        #: never mistake the new deltas' bases for the old chain's
        self._chain = os.urandom(8).hex()
        #: the failover drill's kill target for ``kv_kill_primary_at_step``
        #: — the primary KVStoreServer, when ``store`` is a failover
        #: client rather than the server itself
        self.chaos_primary: Optional[Any] = None
        if register:
            with _ACTIVE_LOCK:
                _ACTIVE.add(self)

    def unregister(self) -> None:
        """Leave the preemption-flush registry (a publisher whose serving
        fleet is torn down should not be flushed to on SIGTERM)."""
        with _ACTIVE_LOCK:
            _ACTIVE.discard(self)

    # ----------------------------------------------------------- properties

    @property
    def generation(self) -> int:
        """The last committed generation (0 before the first publish)."""
        return self._generation

    @property
    def keyframe_generation(self) -> int:
        return self._keyframe_gen

    @property
    def scope(self) -> str:
        return self._scope

    def reconstruction(self) -> Any:
        """What a fully caught-up subscriber holds right now (bit-identical
        by construction — the publisher decodes its own wire)."""
        return self._recon

    # ------------------------------------------------------------ publishing

    def maybe_publish(self, state: Any, step: int) -> Optional[int]:
        """Publish when `step` hits the ``publish_every`` cadence.
        Swallows :class:`PublishError` (serving is best-effort from the
        trainer's point of view — the staleness contract covers the gap);
        :class:`PublishAborted` also ends up here when no elastic driver
        handles it. Returns the committed generation or None."""
        if self._publish_every <= 0 or step % self._publish_every != 0 \
                or step == self._last_step:
            return None
        try:
            return self.publish(state, step)
        except PublishError as e:
            logger.warning("weight publication at step %d failed: %s",
                           step, e)
            return None

    def publish(self, state: Any, step: int, *,
                force_keyframe: bool = False) -> int:
        """Publish one generation from `state`; returns its number.

        Consolidation first (``host_snapshot`` of the extracted tree — an
        owned host copy, so a donated next step cannot invalidate the
        payload mid-upload), then encode, then the commit-last upload
        under the retry policy. Raises :class:`PublishAborted` when the
        elastic fence trips, :class:`PublishError` when the KV stays down
        past the retry budget."""
        from horovod_tpu.training import host_snapshot

        t0 = time.monotonic()
        if _chaos.enabled() and _chaos.take_kv_restart(step):
            # the chaos harness's KV crash: restart in place (WAL replay
            # when configured) at this publish boundary. A store that
            # cannot restart (an HTTP client) fails LOUDLY — the chaos
            # contract is "typos raise, not silently inject nothing",
            # and the injection metric has already counted this charge.
            if not hasattr(self._store, "restart"):
                raise RuntimeError(
                    "HOROVOD_CHAOS kv_restart_at_step armed, but this "
                    "publisher's store is not restartable (pass the "
                    "KVStoreServer, not a client, to chaos-test restarts)"
                )
            self._store.restart()
        if _chaos.enabled() and _chaos.take_kv_kill_primary(step):
            # the control-plane failover drill: SIGKILL-model the primary
            # KV server at this publish boundary. The kill target is
            # ``chaos_primary`` (set by the drill when the publisher's
            # store is a failover CLIENT, as in production) or the store
            # itself; either way a target that cannot be killed fails
            # LOUDLY, same contract as kv_restart_at_step above.
            target = self.chaos_primary or self._store
            if not hasattr(target, "kill"):
                raise RuntimeError(
                    "HOROVOD_CHAOS kv_kill_primary_at_step armed, but "
                    "neither publisher.chaos_primary nor the store is a "
                    "killable KVStoreServer (point chaos_primary at the "
                    "primary to chaos-test failover)"
                )
            target.kill()
        fence0 = self.fence_fn() if self.fence_fn is not None else None
        try:
            tree = host_snapshot(self._extract(state))
        except BaseException as e:
            if _metrics.enabled():
                _metrics.counter(
                    "serving_publish_failures",
                    help="publications abandoned after the retry budget",
                ).inc()
            raise PublishError(
                f"consolidating state for publication failed: {e!r}"
            ) from e
        if self._generation == 0:
            # first publish of this instance: adopt the KV's head so the
            # generation sequence stays MONOTONIC across trainer restarts
            # (a subscriber ignores head <= its own generation — numbers
            # going backward would strand it forever)
            head = self._kv_head()
            if head is not None and head > 0:
                self._generation = head
                # the dead chain's live range is [its keyframe, head]; our
                # first keyframe supersedes all of it, so the GC floor must
                # start there or the old generations leak on the KV forever
                # (re-copied into every WAL compaction). Unreadable head
                # manifest ⇒ the store lost that chain's data anyway.
                self._gc_floor = self._chain_start(head)
        # the gate sits AFTER head adoption so a restarted trainer's
        # rejection reports generations relative to the REAL head the
        # subscribers are serving, not this instance's zero
        reason = self._numerics_gate_reason(state, tree)
        if reason is not None:
            if _metrics.enabled():
                _metrics.counter(
                    "serving_publish_rejected",
                    help="weight generations refused by the numerics gate "
                         "before any byte reached the KV",
                    reason=reason,
                ).inc()
            logger.warning(
                "weight publication at step %d rejected by the numerics "
                "gate (reason=%s); head stays at generation %d",
                step, reason, self._generation,
            )
            raise PublishRejected(reason, self._generation + 1)
        gen = self._generation + 1
        keyframe = (
            force_keyframe
            or self._recon is None
            or gen - self._keyframe_gen >= self._keyframe_every
        )
        if not keyframe and not self._recon_finite:
            # the delta base is poisoned (a gate-less or gate-disabled
            # publisher shipped a non-finite generation): NaN absorbs any
            # delta, so the chain could never recover — a healthy publish
            # re-roots with a keyframe instead of propagating the poison
            # to every subscriber forever
            logger.warning(
                "delta base (generation %d) is non-finite; re-rooting the "
                "chain with a keyframe", self._generation,
            )
            keyframe = True
        if not keyframe and self._kv_head() != self._generation:
            # the KV does not agree with our chain state — it restarted
            # without its WAL (or someone else wrote the scope). A delta
            # would chain onto manifests that no longer exist; a keyframe
            # re-roots the chain unconditionally.
            logger.warning(
                "KV head does not match generation %d; re-rooting the "
                "chain with a keyframe", self._generation,
            )
            keyframe = True
        base = None if keyframe else self._recon
        try:
            payload, info = protocol.encode(tree, base)
        except BaseException as e:
            if base is not None:
                # a delta that cannot be encoded (the published treedef
                # changed, a dtype stopped subtracting) re-roots with a
                # keyframe instead of failing the same way forever
                logger.warning(
                    "delta encode failed (%r); re-rooting with a keyframe",
                    e)
                keyframe, base = True, None
                try:
                    payload, info = protocol.encode(tree, None)
                except BaseException as e2:
                    raise PublishError(
                        f"encoding generation {gen} failed: {e2!r}"
                    ) from e2
            else:
                raise PublishError(
                    f"encoding generation {gen} failed: {e!r}") from e
        chunks = protocol.split_chunks(payload, self._chunk_bytes)
        kf_gen = gen if keyframe else self._keyframe_gen
        manifest = protocol.build_manifest(
            generation=gen, step=step, kind=info["kind"], keyframe=kf_gen,
            chunks=chunks, payload=payload, wire_bytes=info["wire_bytes"],
            elastic_generation=fence0, published_at=time.time(),
            chain=self._chain,
        )

        def _attempt():
            for i, c in enumerate(chunks):
                self._store.put(
                    protocol.chunk_key(self._scope, gen, i), c)
                if i == 0:
                    # chaos: die partway through the upload — chunk 0 is
                    # on the KV, the manifest never will be. The retry
                    # wrapper republishes over the torn remains.
                    _chaos.inject_failure("publish_fail")
            self._check_fence(fence0, gen, len(chunks), manifest=False)
            self._store.put(
                protocol.manifest_key(self._scope, gen), manifest)
            self._check_fence(fence0, gen, len(chunks), manifest=True)
            self._store.put(
                protocol.head_key(self._scope), str(gen).encode())

        try:
            self._retry.call(
                _attempt,
                retriable=self._transient_errors(),
            )
        except PublishAborted:
            raise
        except BaseException as e:
            self._cleanup(gen, len(chunks), manifest=True)
            if _metrics.enabled():
                _metrics.counter(
                    "serving_publish_failures",
                    help="publications abandoned after the retry budget",
                ).inc()
            raise PublishError(
                f"publishing generation {gen} failed: {e!r}") from e

        # committed: advance the chain and track the subscriber view. A
        # keyframe's records are raw, so its decode IS the snapshot we
        # already hold — skip the O(model) deserialize+copy on that path.
        self._recon = tree if keyframe else protocol.decode(payload, base)
        # keyframe finiteness comes from encode() (which already held the
        # host copies); the delta path's recon is host numpy already, so
        # the sweep is isfinite-only — no device transfer either way
        self._recon_finite = (
            bool(info["finite"]) if "finite" in info
            else _tree_finite(self._recon)
        )
        self._generation = gen
        self._keyframe_gen = kf_gen
        self._chunk_counts[gen] = len(chunks)
        self._last_step = step
        dt = time.monotonic() - t0
        if _metrics.enabled():
            kind = info["kind"]
            _metrics.counter(
                "serving_publish_generations",
                help="weight generations committed to the KV",
                kind=kind,
            ).inc()
            _metrics.counter(
                "serving_publish_bytes",
                help="payload bytes published (chunks, before framing)",
            ).inc(len(payload))
            _metrics.gauge(
                "serving_publish_wire_bytes",
                help="array bytes of the last published payload — the "
                     "figure tools/scaling_projection.py::publish_bytes "
                     "models analytically",
                kind=kind,
            ).set(info["wire_bytes"])
            _metrics.gauge(
                "serving_head_generation",
                help="newest committed weight generation",
            ).set(gen)
            _metrics.histogram(
                "serving_publish_seconds",
                help="wall time of one committed publication",
            ).observe(dt)
        # flight ring: a committed generation is a control-plane decision
        # the post-mortem record must carry (was the crash before or
        # after generation G reached subscribers?)
        _flight.record(
            "serve", what="publish", generation=int(gen), step=int(step),
            payload=info["kind"],
        )
        self._gc()
        logger.info(
            "published weight generation %d (%s, step %d, %d bytes, %.3fs)",
            gen, info["kind"], step, len(payload), dt,
        )
        return gen

    def flush(self, state: Any, step: int, *,
              budget_s: float = 5.0) -> Optional[int]:
        """Best-effort final publication inside a bounded budget — the
        preemption-drain path. Forces nothing (a delta is fine: the chain
        stays intact), retries once, never raises. Returns the generation
        or None."""
        policy = _retry.RetryPolicy(
            scope="publish_flush", max_attempts=2, base_delay=0.05,
            max_delay=0.2, deadline=max(0.1, budget_s),
        )
        saved = self._retry
        self._retry = policy
        # the retry deadline only bounds inter-attempt SLEEPS; a single
        # blocked HTTP request rides the store's socket timeout, so clamp
        # that too — a black-holed KV must not turn a 5s flush budget into
        # a 30s-per-chunk hang that eats the checkpoint's grace window
        saved_timeout = getattr(self._store, "request_timeout", None)
        if saved_timeout is not None:
            self._store.request_timeout = min(
                saved_timeout, max(0.5, budget_s))
        try:
            gen = self.publish(state, step)
        except BaseException as e:
            logger.warning("final weight publication failed: %s", e)
            return None
        finally:
            self._retry = saved
            if saved_timeout is not None:
                self._store.request_timeout = saved_timeout
        if _metrics.enabled():
            _metrics.counter(
                "serving_final_flushes",
                help="weight generations flushed during a preemption drain",
            ).inc()
        return gen

    # ------------------------------------------------------------- internals

    @staticmethod
    def _numerics_gate_reason(state, tree) -> Optional[str]:
        """Why this publication must be refused, or None. Delegates to
        :func:`horovod_tpu.resilience.numerics.publish_gate_reason`
        (quarantine pending / trainer mid-bad-streak / non-finite tree);
        an import failure never blocks publication."""
        try:
            from horovod_tpu.resilience import numerics as _numerics
        except Exception as e:
            logger.debug("numerics gate unavailable: %s", e)
            return None
        return _numerics.publish_gate_reason(state, tree)

    def _transient_errors(self):
        from horovod_tpu.run.rendezvous import TRANSIENT_KV_ERRORS

        return TRANSIENT_KV_ERRORS

    def _chain_start(self, head: int) -> int:
        """Keyframe generation of the chain `head` belongs to, from its
        manifest; ``head + 1`` when unreadable (nothing left to GC)."""
        from horovod_tpu.run.rendezvous import DeadRankError

        try:
            blob = self._store.get(
                protocol.manifest_key(self._scope, head))
            if blob is None:
                return head + 1
            return int(protocol.parse_manifest(blob)["keyframe"])
        except (DeadRankError, protocol.ChainError, _retry.RetryError,
                ValueError, TypeError):
            return head + 1
        except self._transient_errors():
            return head + 1

    def _kv_head(self) -> Optional[int]:
        """The committed head as the KV sees it (None when unreadable —
        missing, tombstoned, or the KV is down; the delta/keyframe decision
        treats every one of those as "chain not intact")."""
        from horovod_tpu.run.rendezvous import DeadRankError

        try:
            blob = self._store.get(protocol.head_key(self._scope))
            return None if blob is None else int(blob)
        except (DeadRankError, ValueError, _retry.RetryError):
            return None
        except self._transient_errors():
            return None

    def _check_fence(self, fence0, gen: int, n_chunks: int,
                     *, manifest: bool) -> None:
        if self.fence_fn is None:
            return
        if self.fence_fn() == fence0:
            return
        self._cleanup(gen, n_chunks, manifest=manifest)
        if _metrics.enabled():
            _metrics.counter(
                "serving_publish_aborts",
                help="in-flight generations aborted by the elastic fence",
            ).inc()
        raise PublishAborted(
            f"elastic generation changed mid-publish (was {fence0}); "
            f"aborted in-flight weight generation {gen}"
        )

    def _cleanup(self, gen: int, n_chunks: int, *, manifest: bool) -> None:
        """Delete the partial remains of an uncommitted generation; the
        head never pointed at it, so this is purely hygiene (best-effort:
        an unreachable KV keeps the garbage until the next overwrite)."""
        try:
            if manifest:
                self._store.delete(protocol.manifest_key(self._scope, gen))
            for i in range(n_chunks):
                self._store.delete(protocol.chunk_key(self._scope, gen, i))
        except Exception as e:
            logger.debug("aborted-generation cleanup incomplete: %s", e)

    def _gc(self) -> None:
        """Retire generations older than the newest keyframe: a subscriber
        can always resync from the keyframe, so nothing before it is
        reachable. Manifests are tombstoned (a lagging subscriber's GET
        sees "GC'd", not "never written"); chunks are plain-deleted."""
        n = 0
        while self._gc_floor < self._keyframe_gen:
            g = self._gc_floor
            try:
                n_chunks = self._chunk_counts.get(g)
                if n_chunks is None:
                    # an adopted dead chain's generation: its chunk count
                    # lives only in its manifest — read before tombstoning
                    try:
                        blob = self._store.get(
                            protocol.manifest_key(self._scope, g))
                        n_chunks = (
                            int(protocol.parse_manifest(blob)["chunks"])
                            if blob is not None else 1
                        )
                    except Exception:
                        # unreadable/tombstoned manifest must not stall
                        # the floor — delete what we can and move on
                        n_chunks = 1
                self._store.delete(
                    protocol.manifest_key(self._scope, g), tombstone=True)
                for i in range(n_chunks):
                    self._store.delete(protocol.chunk_key(self._scope, g, i))
            except Exception:
                return  # retry from the same floor next publish
            self._chunk_counts.pop(g, None)
            self._gc_floor = g + 1
            n += 1
        if n and _metrics.enabled():
            _metrics.counter(
                "serving_generations_gc",
                help="superseded weight generations retired from the KV",
            ).inc(n)
