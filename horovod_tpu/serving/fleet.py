"""Fleet serving tier: replica failover, hedged routing, coordinated rollout.

ISSUE 17 — the multi-replica control plane the single-process serving
stack (PR 12's engine, PR 16's SLOs) was scoped for. N
:class:`~horovod_tpu.serving.engine.InferenceEngine` replicas, each
riding its OWN :class:`~horovod_tpu.serving.subscriber.WeightSubscriber`
off the same publication chain, sit behind a :class:`FleetRouter`:

- **Routing** scores live queue depth + page-pool occupancy per replica
  (published through the same metrics plane ``/fleet`` aggregates), with
  a stale replica (subscriber ``stale()`` true, or a ``replica_stale``
  chaos charge) demoted to *last resort*: the router never picks it
  while any fresh replica has capacity, and the PR-12 staleness→health
  path keeps firing underneath — each pump feeds the *worst* replica's
  staleness view to the health plane, so ``/health`` answers 503 while
  ANY replica serves stale weights.
- **Retry / hedging** ride the shared
  :class:`~horovod_tpu.resilience.retry.RetryPolicy` under the ``ROUTE``
  scope (``HOROVOD_RETRY_ROUTE_*``: exp backoff + jitter + total
  deadline), seeded per request from the same crc32 the canary router
  hashes — a given rid's retry schedule is deterministic and replayable.
  A request in flight longer than ``HOROVOD_FLEET_HEDGE_AFTER`` seconds
  grows a duplicate copy on the next-best replica
  (``fleet_requests_hedged``); a request whose every copy rode a dead
  replica is resubmitted (``fleet_requests_failed_over``). The first
  completion wins, losers are cancelled at a pump boundary — exactly
  once, never double-completed, request ids stable throughout.
- **Drain** quiesces a replica (no new routes), finishes its in-flight
  work, then deregisters it by *tombstoning* its rendezvous-KV TTL lease
  (the elastic heartbeat pattern): an expired lease means "vanished", a
  tombstone means "left cleanly".
- **Fleet-wide rollout** (:class:`FleetRollout`) promotes the PR-12/16
  canary state machine from per-engine to one generation-fenced decision
  log in the rendezvous KV, committed decision-record-first and head
  pointer last (the :class:`~horovod_tpu.serving.publisher
  .WeightPublisher` commit-last idiom): replicas apply decisions in
  epoch order, the gate judges PR 16's
  :meth:`~horovod_tpu.observability.slo.SLORegistry.judge_canary` over
  *fleet-merged* per-arm windows, and a vetoed generation can never be
  serving on replica 2 after replica 1 rolled it back — there is no
  per-replica verdict to disagree about.

Chaos drills: ``replica_kill=<i>[:<at_pump>]`` kills replica `i`
mid-decode at a pump boundary (the router must re-route with
exactly-once completion); ``replica_stale=<i>:<s>`` forces replica `i`
stale; ``slow_decode=<s>:<arm>@<replica>`` scopes the latency regression
to one replica's arm.

Env knobs: ``HOROVOD_FLEET_HEDGE_AFTER`` (seconds in flight before a
request is hedged to a second replica; 0 disables, default 0.25) and
``HOROVOD_FLEET_STATUS_TTL`` (TTL on each replica's KV lease + status
blob, default 10, the elastic heartbeat default).

stdlib-only at module level; everything jax stays inside the engines.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from horovod_tpu.observability import flight as _flight
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import reqtrace as _reqtrace
from horovod_tpu.observability import trace as _trace
from horovod_tpu.resilience import chaos as _chaos
from horovod_tpu.resilience import health as _health
from horovod_tpu.resilience.retry import RetryError, policy_from_env
from horovod_tpu.serving.engine import note_subscriber_health
from horovod_tpu.serving.rollout import judge_window
from horovod_tpu.serving.rollout import (
    CANARY_FRACTION_ENV,
    CANARY_MIN_REQUESTS_ENV,
)
from horovod_tpu.serving.scheduler import (
    QueueFull,
    Request,
    prefix_digests,
)

__all__ = [
    "FleetSaturated",
    "FleetReplica",
    "FleetRequest",
    "FleetRouter",
    "FleetRollout",
    "HEDGE_AFTER_ENV",
    "STATUS_TTL_ENV",
]

logger = logging.getLogger("horovod_tpu.serving")

HEDGE_AFTER_ENV = "HOROVOD_FLEET_HEDGE_AFTER"
STATUS_TTL_ENV = "HOROVOD_FLEET_STATUS_TTL"

#: fleet_serving_replica_state encoding
STATE_HEALTHY = 0
STATE_STALE = 1
STATE_DRAINING = 2
STATE_DEAD = 3
STATE_DRAINED = 4

_STATE_NAMES = {
    STATE_HEALTHY: "healthy",
    STATE_STALE: "stale",
    STATE_DRAINING: "draining",
    STATE_DEAD: "dead",
    STATE_DRAINED: "drained",
}


class FleetSaturated(QueueFull):
    """Every live replica rejected the request and the ROUTE retry
    budget (attempts + deadline) is spent. Inherits the
    ``retry_after_s`` hint — the *minimum* backlog estimate across the
    fleet, since the caller's retry only needs ONE replica to clear."""


class FleetReplica:
    """One engine + its own subscriber, registered under a fleet id.

    Liveness is a rendezvous-KV TTL lease
    (``/<scope>/replica/<id>``, the elastic heartbeat pattern) the
    router refreshes every pump; a compact status blob
    (``/<scope>/status/<id>``) rides the same store so scoring works
    across processes through the same KV the weights travel on.
    Deregistration *tombstones* the lease — an observer can tell
    "drained cleanly" from "lease expired, replica vanished".

    The replica also quacks like a subscriber (``lag()`` /
    ``staleness_seconds()`` / ``stale()``) so the PR-12 staleness→health
    bridge (:func:`~horovod_tpu.serving.engine.note_subscriber_health`)
    can consume it, with the ``replica_stale`` chaos charge layered on
    top of the real subscriber watermark. The router runs that bridge
    ONCE per pump against the stalest live replica — the health monitor
    is process-global, so per-replica calls would let a fresh replica
    polled last clear a degradation a stale sibling still owns.
    """

    def __init__(self, replica_id: str, engine, subscriber=None, *,
                 store=None, scope: str = "fleetserve",
                 lease_ttl: Optional[float] = None):
        self.id = str(replica_id)
        self.engine = engine
        self.subscriber = subscriber
        engine.replica = self.id
        self._store = store
        self._scope = scope.strip("/")
        self.lease_ttl = float(
            lease_ttl if lease_ttl is not None
            else os.environ.get(STATUS_TTL_ENV, "10.0"))
        #: fleet-assigned position — chaos charges target this index
        self.index: int = -1
        self.draining = False
        self.dead = False
        self.deregistered = False
        self.stable_generation: Optional[int] = None
        self.canary_generation: Optional[int] = None
        #: rollout-decision fence: epochs <= this have been applied
        self.applied_epoch = 0

    # ------------------------------------------------------------- lease

    @property
    def lease_key(self) -> str:
        return f"/{self._scope}/replica/{self.id}"

    @property
    def status_key(self) -> str:
        return f"/{self._scope}/status/{self.id}"

    def heartbeat(self) -> None:
        if self._store is None or self.dead or self.deregistered:
            return
        self._store.put(self.lease_key, b"1", ttl=self.lease_ttl)

    def deregister(self) -> None:
        """Clean exit: tombstone the lease (distinct from expiry) and
        drop the status blob."""
        self.deregistered = True
        if self._store is not None:
            self._store.delete(self.lease_key, tombstone=True)
            self._store.delete(self.status_key)

    def kill(self) -> None:
        """Fail the replica where it stands: lease tombstoned, in-flight
        sequences abandoned mid-decode (their requests never complete
        here — the router re-routes them)."""
        self.dead = True
        if self._store is not None:
            self._store.delete(self.lease_key, tombstone=True)
            self._store.delete(self.status_key)

    # --------------------------------------------------- staleness facade

    def forced_stale_seconds(self) -> Optional[float]:
        charge = _chaos.replica_stale()
        if charge is None or int(charge[0]) != self.index:
            return None
        return float(charge[1])

    def lag(self) -> int:
        if self.subscriber is None:
            return 0
        return int(self.subscriber.lag())

    def staleness_seconds(self) -> Optional[float]:
        forced = self.forced_stale_seconds()
        if forced is not None:
            return forced
        if self.subscriber is None:
            return None
        return self.subscriber.staleness_seconds()

    def stale(self) -> bool:
        if self.dead:
            return True
        if self.forced_stale_seconds() is not None:
            return True
        if self.subscriber is None:
            return False
        return bool(self.subscriber.stale())

    def poll(self) -> None:
        """Advance the subscriber; the router's fleet-level health
        bridge (one call per pump, stalest replica wins) handles the
        PR-12 503/DEGRADED path."""
        if self.dead or self.subscriber is None:
            return
        self.subscriber.poll()

    # ------------------------------------------------------------- status

    def state_code(self) -> int:
        if self.dead:
            return STATE_DEAD
        if self.deregistered:
            return STATE_DRAINED
        if self.draining:
            return STATE_DRAINING
        if self.stale():
            return STATE_STALE
        return STATE_HEALTHY

    def queue_depth(self) -> int:
        return int(self.engine.scheduler.queue_depth())

    def pages_in_use(self) -> int:
        return int(self.engine.scheduler.pages_in_use())

    def active_sequences(self) -> int:
        return len(self.engine.scheduler.active())

    def prefix_summary(self) -> List[str]:
        """Content block digests of this replica's resident prefix
        cache — the locality signal the router scores against."""
        return list(self.engine.scheduler.prefix_summary())

    def status(self) -> Dict[str, Any]:
        age = self.staleness_seconds()
        return {
            "id": self.id,
            "index": self.index,
            "state": _STATE_NAMES[self.state_code()],
            "queue_depth": self.queue_depth(),
            "active": self.active_sequences(),
            "pages_in_use": self.pages_in_use(),
            "free_pages": int(self.engine.scheduler.free_page_count()),
            "stale": self.stale(),
            "staleness_seconds": None if age is None else float(age),
            "lag": self.lag(),
            "stable_generation": self.stable_generation,
            "canary_generation": self.canary_generation,
            "applied_epoch": self.applied_epoch,
            # prefix-cache advertisement: page granularity + resident
            # block hashes, so any router (in- or out-of-process) can
            # fold prefix locality into its scoring
            "prefix_page_size": int(self.engine.page_size),
            "prefix_blocks": self.prefix_summary(),
        }

    def publish_status(self) -> None:
        """One pump's worth of liveness + scoring signal: refresh the
        TTL lease, write the status blob, land the per-replica gauges
        (which ride the ``/fleet`` aggregation plane like every other
        metric)."""
        if self.dead or self.deregistered:
            return
        if self.forced_stale_seconds() is not None:
            _chaos.record_injection("replica_stale")
        self.heartbeat()
        st = self.status()
        if self._store is not None:
            self._store.put(self.status_key,
                            json.dumps(st).encode(),
                            ttl=self.lease_ttl)
        if _metrics.enabled():
            _metrics.gauge(
                "fleet_serving_replica_queue_depth",
                help="requests queued on each fleet replica",
                replica=self.id,
            ).set(st["queue_depth"])
            _metrics.gauge(
                "fleet_serving_replica_pages_in_use",
                help="kv-cache pages reserved on each fleet replica",
                replica=self.id,
            ).set(st["pages_in_use"])
            if st["staleness_seconds"] is not None:
                _metrics.gauge(
                    "fleet_serving_replica_staleness_seconds",
                    help="wall-clock age of the weights each fleet "
                         "replica serves",
                    replica=self.id,
                ).set(st["staleness_seconds"])
            _metrics.gauge(
                "fleet_serving_replica_state",
                help="0 healthy, 1 stale, 2 draining, 3 dead, 4 drained",
                replica=self.id,
            ).set(self.state_code())


class FleetRequest:
    """One fleet-level request: a stable rid, one or more engine-level
    copies (the primary, hedges, failover resubmissions), and exactly
    one completion — the first copy to finish wins, the rest are
    cancelled at a pump boundary."""

    def __init__(self, rid, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, arm: str = "stable"):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.arm = arm
        self.submitted_at = time.monotonic()
        #: (replica, engine-level Request) per copy, submission order
        self.copies: List[Tuple[FleetReplica, Request]] = []
        self.hedged = False
        self.failovers = 0
        self.result: Optional[Request] = None
        self.error: Optional[str] = None
        self.finished_at: Optional[float] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def tokens(self):
        return None if self.result is None else self.result.tokens

    @property
    def generated(self):
        return None if self.result is None else self.result.generated

    @property
    def replica(self) -> Optional[str]:
        """Id of the replica whose copy won (None until completion)."""
        if self.result is None:
            return None
        return str(getattr(self.result, "replica", "") or "") or None

    def latency_seconds(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class FleetRouter:
    """Health-aware router over N engine replicas.

    Scoring is a lexicographic tuple per live (not dead, not draining)
    replica: ``(stale-tier, queue_depth + active + pages_fraction,
    index)`` — a stale replica only ever takes traffic when every fresh
    replica rejected (last resort), load balances within a tier, and the
    index breaks ties deterministically. One submission *attempt* sweeps
    the candidates in score order; all-rejected attempts retry under the
    shared ROUTE :class:`~horovod_tpu.resilience.retry.RetryPolicy`
    (``HOROVOD_RETRY_ROUTE_*``), seeded from the rid's crc32 so the
    backoff schedule is per-request deterministic. Exhaustion raises
    :class:`FleetSaturated` carrying the fleet-minimum
    ``retry_after_s`` backpressure hint.

    :meth:`pump` is the serving loop turn: fire chaos, step every live
    engine, harvest completions (first copy wins, losers cancelled),
    fail over requests stranded on dead replicas, hedge slow ones,
    advance the attached :class:`FleetRollout`, publish statuses.
    """

    def __init__(self, *, store=None, scope: str = "fleetserve",
                 retry_policy=None, hedge_after: Optional[float] = None,
                 lease_ttl: Optional[float] = None):
        self._store = store
        self._scope = scope
        self._lease_ttl = lease_ttl
        self._policy = retry_policy if retry_policy is not None \
            else policy_from_env("route", max_attempts=3,
                                 base_delay=0.02, max_delay=0.5,
                                 deadline=2.0)
        self.hedge_after = float(
            hedge_after if hedge_after is not None
            else os.environ.get(HEDGE_AFTER_ENV, "0.25"))
        self._replicas: Dict[str, FleetReplica] = {}
        self._order: List[str] = []
        self._outstanding: List[FleetRequest] = []
        #: id(engine Request) → (fleet request, replica that ran it)
        self._by_copy: Dict[int, Tuple[FleetRequest, FleetReplica]] = {}
        #: replica id → arm → bounded completion entries (the fleet
        #: rollout's gate windows, fed by the reqtrace observer)
        self._windows: Dict[str, Dict[str, deque]] = {}
        self._rollout: Optional["FleetRollout"] = None
        self._pump_count = 0
        _reqtrace.add_completion_observer(self._on_completion)

    # ------------------------------------------------------------ fleet

    def add_replica(self, replica_id: str, engine, subscriber=None,
                    **kw) -> FleetReplica:
        """Register a replica (engine + its own subscriber); the fleet
        index it gets is what ``replica_kill=<i>`` / ``replica_stale=<i>``
        chaos charges target."""
        r = FleetReplica(replica_id, engine, subscriber,
                         store=self._store, scope=self._scope,
                         lease_ttl=kw.get("lease_ttl", self._lease_ttl))
        r.index = len(self._order)
        self._replicas[r.id] = r
        self._order.append(r.id)
        r.heartbeat()
        if self._rollout is not None:
            self._rollout.catch_up(r)
        return r

    @property
    def replicas(self) -> List[FleetReplica]:
        return [self._replicas[rid] for rid in self._order]

    def replica(self, replica_id: str) -> FleetReplica:
        return self._replicas[str(replica_id)]

    def live_replicas(self, include_draining: bool = False
                      ) -> List[FleetReplica]:
        return [r for r in self.replicas
                if not r.dead and not r.deregistered
                and (include_draining or not r.draining)]

    def attach_rollout(self, rollout: "FleetRollout") -> None:
        self._rollout = rollout

    def close(self) -> None:
        """Detach from the reqtrace observer list (tests / shutdown)."""
        _reqtrace.remove_completion_observer(self._on_completion)

    # ---------------------------------------------------------- scoring

    def _score(self, r: FleetReplica,
               affinity: int = 0) -> Tuple[int, float, int, int]:
        """Lexicographic routing score (lower is better): staleness
        tier, load, then prefix affinity (negated: more matched blocks
        ranks earlier), then the stable index tiebreak. Affinity is
        DEMOTED below staleness and load by construction — a cache-warm
        but stale or overloaded replica never beats a healthy one."""
        pool = max(1, int(r.engine.num_pages) - 1)
        load = (r.queue_depth() + r.active_sequences()
                + r.pages_in_use() / pool)
        return (1 if r.stale() else 0, load, -int(affinity), r.index)

    def _affinity(self, digests: List[str], r: FleetReplica) -> int:
        """Consecutive leading prompt blocks resident in `r`'s prefix
        cache — the run length is what an admission hit could alias."""
        if not digests:
            return 0
        resident = set(r.prefix_summary())
        n = 0
        for d in digests:
            if d not in resident:
                break
            n += 1
        return n

    def candidates(self, arm: str = "stable",
                   prompt=None) -> List[FleetReplica]:
        """Live replicas in routing order for `arm` — canary traffic
        only goes where the fleet's canary generation is actually
        installed. With `prompt`, replicas already holding its prefix
        blocks sort earlier within a staleness/load tier (requests
        sharing prefixes land where the pages live)."""
        out = self.live_replicas()
        if arm == "canary":
            want = None if self._rollout is None \
                else self._rollout.canary_generation
            out = [r for r in out
                   if want is not None
                   and r.engine.arm_generation("canary") == want]
        aff: Dict[str, int] = {}
        if prompt is not None:
            digs: Dict[int, List[str]] = {}
            for r in out:
                ps = int(r.engine.page_size)
                if ps not in digs:
                    digs[ps] = prefix_digests(prompt, ps)
                aff[r.id] = self._affinity(digs[ps], r)
        return sorted(
            out, key=lambda r: self._score(r, aff.get(r.id, 0)))

    # ---------------------------------------------------------- intake

    def route(self, rid) -> str:
        """Deterministic arm split — the fleet rollout's canary slice
        (crc32, same hash as the per-engine router) or stable."""
        if self._rollout is None:
            return "stable"
        return self._rollout.route(rid)

    def submit(self, rid, prompt, max_new_tokens: int,
               temperature: float = 0.0) -> FleetRequest:
        """Route one request into the fleet; raises
        :class:`FleetSaturated` (with a ``retry_after_s`` hint) only
        after the ROUTE retry budget is spent against a fully saturated
        fleet."""
        freq = FleetRequest(rid, prompt, max_new_tokens,
                            temperature=temperature,
                            arm=self.route(rid))
        policy = dataclasses.replace(
            self._policy, seed=zlib.crc32(str(rid).encode()))

        def attempt() -> FleetReplica:
            cands = self.candidates(freq.arm, prompt=freq.prompt)
            if not cands and freq.arm == "canary":
                # no replica holds the canary generation (yet): the
                # stable arm serves the request rather than dropping it
                freq.arm = "stable"
                cands = self.candidates("stable", prompt=freq.prompt)
            if not cands:
                raise QueueFull("no live replica in the fleet")
            last: Optional[QueueFull] = None
            for r in cands:
                try:
                    self._submit_copy(freq, r)
                    return r
                except QueueFull as e:
                    last = e
            assert last is not None
            raise last

        try:
            chosen = policy.call(attempt, retriable=(QueueFull,))
        except RetryError as e:
            hints = [r.engine.scheduler.backpressure_hint()
                     for r in self.live_replicas()]
            hint = min(hints) if hints else None
            freq.error = (f"rejected: fleet saturated "
                          f"(route retries exhausted: {e})")
            freq.finished_at = time.monotonic()
            freq._done.set()
            if _metrics.enabled():
                _metrics.counter(
                    "fleet_requests",
                    help="fleet-level requests completed, by arm and "
                         "outcome",
                    arm=freq.arm, outcome="rejected",
                ).inc()
            raise FleetSaturated(
                f"every live replica rejected request {rid!r}; retry "
                + (f"in ~{hint:.3f}s" if hint is not None else "later"),
                retry_after_s=hint) from e
        self._outstanding.append(freq)
        self._span("route", rid, replica=chosen.id, arm=freq.arm)
        return freq

    def _submit_copy(self, freq: FleetRequest, r: FleetReplica) -> None:
        req = Request(freq.rid, freq.prompt, freq.max_new_tokens,
                      temperature=freq.temperature, arm=freq.arm)
        # stamped BEFORE submit so reqtrace's req_begin carries it
        req.replica = r.id
        r.engine.submit(req)
        freq.copies.append((r, req))
        self._by_copy[id(req)] = (freq, r)

    # --------------------------------------------------------- the loop

    def pump(self) -> bool:
        """One fleet serving-loop turn. Returns True while any engine
        made progress."""
        self._pump_count += 1
        self._chaos_kill()
        ran = False
        for r in self.live_replicas(include_draining=True):
            if self._rollout is None:
                r.poll()
            ran = bool(r.engine.step()) or ran
        self._harvest()
        self._hedge()
        if self._rollout is not None:
            self._rollout.advance()
        self._note_fleet_health()
        for r in self.replicas:
            r.publish_status()
        return ran

    def _note_fleet_health(self) -> None:
        """One PR-12 staleness→health bridge call per pump, fed the
        STALEST live replica: ``/health`` answers 503 while any replica
        serves stale weights, and recovers only once none does (the
        health monitor is process-global — per-replica calls would let
        the last-polled fresh replica clear a stale sibling's
        degradation)."""
        live = self.live_replicas(include_draining=True)
        if not live:
            return
        stale = [r for r in live if r.stale()]
        pick = max(stale, key=lambda r: r.staleness_seconds() or 0.0) \
            if stale else live[0]
        note_subscriber_health(pick)

    def drain(self, max_iters: int = 10000) -> None:
        """Pump until every outstanding fleet request completed."""
        for _ in range(max_iters):
            self._outstanding = [f for f in self._outstanding
                                 if not f.done]
            if not self._outstanding:
                return
            self.pump()
        raise RuntimeError(
            f"fleet did not drain within {max_iters} iterations")

    def drain_replica(self, replica_id: str,
                      max_iters: int = 10000) -> None:
        """Graceful exit for one replica: quiesce (no new routes),
        finish its in-flight work, deregister (tombstoned lease)."""
        r = self._replicas[str(replica_id)]
        r.draining = True
        for _ in range(max_iters):
            if r.engine.scheduler.idle():
                break
            self.pump()
        else:
            raise RuntimeError(
                f"replica {replica_id!r} did not quiesce within "
                f"{max_iters} iterations")
        r.deregister()
        logger.info("fleet: replica %s drained and deregistered", r.id)

    def kill_replica(self, replica_id: str,
                     reason: str = "killed") -> None:
        r = self._replicas[str(replica_id)]
        if r.dead:
            return
        r.kill()
        # close the victim's in-flight copies in reqtrace (host-side
        # bookkeeping only — the dead engine never steps again). In a
        # real fleet the dead process takes its trace table with it;
        # in-process the abandoned rids would sit in live_requests()
        # forever. Cancelled completions never reach the gate windows.
        for freq in self._outstanding:
            for rep, copy in freq.copies:
                if rep is r and not copy.done:
                    r.engine.scheduler.cancel(
                        copy, reason="cancelled: replica dead")
        _health.record_replica_lost(r.id, reason)
        _flight.record("fleet", what="replica_dead", replica=r.id,
                       reason=reason)
        logger.warning("fleet: replica %s lost (%s); re-routing its "
                       "in-flight requests", r.id, reason)

    def _chaos_kill(self) -> None:
        idx = _chaos.take_replica_kill(self._pump_count)
        if idx is None:
            return
        victim = next((r for r in self.replicas
                       if r.index == idx and not r.dead), None)
        if victim is not None:
            self.kill_replica(victim.id, reason="chaos replica_kill")

    # -------------------------------------------------------- completion

    def _on_completion(self, req, summary: Dict[str, Any]) -> None:
        """reqtrace completion observer: feed the per-replica gate
        windows (cancelled hedge losers excluded — they were never a
        served outcome)."""
        entry = self._by_copy.get(id(req))
        if entry is None or summary.get("cancelled"):
            return
        _freq, r = entry
        per_arm = self._windows.setdefault(r.id, {})
        win = per_arm.get(summary["arm"])
        if win is None:
            win = deque(maxlen=_reqtrace.window_size())
            per_arm[summary["arm"]] = win
        win.append({
            "generation": int(summary["generation"]),
            "error": summary["error"],
            "e2e": summary["e2e"],
            "ttft": summary["ttft"],
            "tpot_mean": summary["tpot_mean"],
        })

    def merged_window(self, arm: str,
                      generation: Optional[int] = None
                      ) -> Dict[str, object]:
        """Fleet-merged completion window for `arm` (all replicas,
        optionally generation-filtered) in the
        :func:`~horovod_tpu.observability.reqtrace.arm_window` dict
        shape — what the fleet rollout gate judges."""
        entries: List[dict] = []
        for per_arm in self._windows.values():
            entries.extend(
                e for e in per_arm.get(arm, ())
                if generation is None
                or e["generation"] == int(generation))
        e2e = [e["e2e"] for e in entries if e["e2e"] is not None]
        return {
            "done": len(entries),
            "errors": sum(1 for e in entries if e["error"]),
            "latency_sum": float(sum(e2e)),
            "e2e": e2e,
            "ttft": [e["ttft"] for e in entries
                     if e["ttft"] is not None],
            "tpot": [e["tpot_mean"] for e in entries
                     if e["tpot_mean"] is not None],
        }

    def reset_windows(self) -> None:
        """Fresh gate windows (a new canary epoch starts its own
        evaluation, the per-engine ``_reset_window`` idiom)."""
        self._windows.clear()

    def _harvest(self) -> None:
        still: List[FleetRequest] = []
        for freq in self._outstanding:
            if freq.done:
                continue
            winner: Optional[Tuple[FleetReplica, Request]] = None
            errored: Optional[Tuple[FleetReplica, Request]] = None
            live_copies = 0
            for r, c in freq.copies:
                if r.dead:
                    continue
                if not c.done:
                    live_copies += 1
                    continue
                if c.error is None:
                    winner = (r, c)
                    break
                if not str(c.error).startswith("cancelled"):
                    errored = (r, c)
            if winner is not None:
                self._complete(freq, *winner)
                continue
            if live_copies == 0:
                if errored is not None:
                    # a genuine engine error (not a dead replica): the
                    # same weights serve everywhere, re-routing would
                    # reproduce it — the error IS the result
                    self._complete(freq, *errored)
                    continue
                self._failover(freq)
                if not freq.done:
                    still.append(freq)
                continue
            still.append(freq)
        self._outstanding = still

    def _complete(self, freq: FleetRequest, r: FleetReplica,
                  copy: Request) -> None:
        freq.result = copy
        freq.error = copy.error
        freq.finished_at = time.monotonic()
        freq._done.set()
        for other_r, other_c in freq.copies:
            if other_c is copy or other_c.done or other_r.dead:
                continue
            other_r.engine.scheduler.cancel(
                other_c, reason="cancelled: superseded by "
                f"replica {r.id}")
        # release the copy table entries — the request is settled
        for _r2, c2 in freq.copies:
            self._by_copy.pop(id(c2), None)
        if _metrics.enabled():
            _metrics.counter(
                "fleet_requests",
                help="fleet-level requests completed, by arm and "
                     "outcome",
                arm=freq.arm,
                outcome="error" if freq.error else "ok",
            ).inc()
        self._span("complete", freq.rid, replica=r.id,
                   outcome="error" if freq.error else "ok")

    def _failover(self, freq: FleetRequest) -> None:
        """Every copy of `freq` rode a dead replica: resubmit to the
        best live one (exactly-once is preserved — dead copies can
        never complete)."""
        cands = self.candidates(freq.arm)
        if not cands and freq.arm == "canary":
            freq.arm = "stable"
            cands = self.candidates("stable")
        for r in cands:
            try:
                self._submit_copy(freq, r)
            except QueueFull:
                continue
            freq.failovers += 1
            if _metrics.enabled():
                _metrics.counter(
                    "fleet_requests_failed_over",
                    help="requests re-routed off a dead replica",
                ).inc()
            self._span("failover", freq.rid, replica=r.id)
            logger.info("fleet: request %r failed over to replica %s",
                        freq.rid, r.id)
            return
        if not cands:
            freq.error = "rejected: no live replica to re-route to"
            freq.finished_at = time.monotonic()
            freq._done.set()
            if _metrics.enabled():
                _metrics.counter(
                    "fleet_requests",
                    help="fleet-level requests completed, by arm and "
                         "outcome",
                    arm=freq.arm, outcome="rejected",
                ).inc()
        # all candidates full: leave outstanding, next pump retries

    def _hedge(self) -> None:
        if self.hedge_after <= 0:
            return
        now = time.monotonic()
        for freq in self._outstanding:
            if freq.done or freq.hedged:
                continue
            if now - freq.submitted_at < self.hedge_after:
                continue
            riding = {r.id for r, c in freq.copies
                      if not r.dead and not c.done}
            if not riding:
                continue  # the failover path owns this one
            for r in self.candidates(freq.arm):
                if r.id in riding:
                    continue
                try:
                    self._submit_copy(freq, r)
                except QueueFull:
                    continue
                freq.hedged = True
                if _metrics.enabled():
                    _metrics.counter(
                        "fleet_requests_hedged",
                        help="requests duplicated onto a second "
                             "replica after the hedge deadline",
                    ).inc()
                self._span("hedge", freq.rid, replica=r.id)
                break

    # ---------------------------------------------------------- plumbing

    def _span(self, what: str, rid, **args) -> None:
        if not _reqtrace.enabled() or not _trace.enabled():
            return
        _trace.add_raw({
            "ph": "i", "s": "t", "pid": "fleet-router", "tid": "route",
            "name": f"{what}:{rid}",
            "ts": round(_trace.rel_us(time.monotonic()), 1),
            "args": args,
        })


class FleetRollout:
    """Fleet-wide canary state machine: ONE generation-fenced decision,
    coordinated through the rendezvous KV.

    Decisions (``bootstrap`` / ``canary`` / ``promote`` / ``rollback``)
    are a monotone epoch log under ``/<scope>/rollout/decision/<epoch>``
    with a head pointer at ``/<scope>/rollout/epoch`` written LAST (the
    WeightPublisher commit-last idiom): a reader that sees the head sees
    the whole decision. Replicas apply decisions strictly in epoch order
    behind their own ``applied_epoch`` fence — a replica that cannot yet
    apply (its subscriber hasn't caught up to the decision's generation)
    blocks there rather than skipping ahead, so no interleaving leaves
    two replicas serving different verdicts about the same generation.

    The gate is :func:`horovod_tpu.serving.rollout.judge_window` — the
    SAME error-rate / latency-ratio /
    :meth:`~horovod_tpu.observability.slo.SLORegistry.judge_canary`
    logic the per-engine rollout uses — judged over the router's
    *fleet-merged* per-arm windows, so one slow replica's canary burn
    rolls the generation back everywhere and a vetoed generation can
    never be serving on any replica afterwards.
    """

    def __init__(self, router: FleetRouter, store=None, *,
                 scope: str = "fleetserve",
                 canary_fraction: Optional[float] = None,
                 min_canary_requests: Optional[int] = None,
                 max_error_rate: float = 0.0,
                 max_latency_ratio: Optional[float] = 3.0,
                 slo=None,
                 on_event: Optional[Callable[[str, int], None]] = None):
        self._router = router
        self._store = store if store is not None \
            else router._store
        self._mem: Dict[str, bytes] = {}
        self._scope = scope.strip("/")
        self.canary_fraction = float(
            canary_fraction if canary_fraction is not None
            else os.environ.get(CANARY_FRACTION_ENV, "0.25"))
        self.min_canary_requests = int(
            min_canary_requests if min_canary_requests is not None
            else os.environ.get(CANARY_MIN_REQUESTS_ENV, "8"))
        self.max_error_rate = float(max_error_rate)
        self.max_latency_ratio = max_latency_ratio
        self._slo = slo
        self._on_event = on_event
        self._stable_gen: Optional[int] = None
        self._canary_gen: Optional[int] = None
        self._vetoed: set = set()
        self._epoch = 0
        router.attach_rollout(self)
        self._record_state()

    # ------------------------------------------------------------ views

    @property
    def stable_generation(self) -> Optional[int]:
        return self._stable_gen

    @property
    def canary_generation(self) -> Optional[int]:
        return self._canary_gen

    @property
    def vetoed(self) -> frozenset:
        return frozenset(self._vetoed)

    @property
    def epoch(self) -> int:
        return self._epoch

    def route(self, rid) -> str:
        """The PR-12 deterministic slice, fleet-wide: same crc32 hash,
        same fraction, one decision for every replica."""
        if self._canary_gen is None:
            return "stable"
        h = zlib.crc32(str(rid).encode()) % 10000
        return ("canary" if h < int(self.canary_fraction * 10000)
                else "stable")

    # --------------------------------------------------------- decisions

    def _kv_put(self, key: str, rec: Dict[str, Any]) -> None:
        blob = json.dumps(rec).encode()
        full = f"/{self._scope}/rollout/{key}"
        if self._store is not None:
            self._store.put(full, blob)
        else:
            self._mem[full] = blob

    def _kv_get(self, key: str) -> Optional[Dict[str, Any]]:
        full = f"/{self._scope}/rollout/{key}"
        blob = self._store.get(full) if self._store is not None \
            else self._mem.get(full)
        if blob is None:
            return None
        return json.loads(blob.decode())

    def head_epoch(self) -> int:
        head = self._kv_get("epoch")
        return 0 if head is None else int(head["epoch"])

    def _commit(self, action: str, generation: int) -> None:
        """Commit-last: the decision record lands before the head
        pointer moves, so no replica can observe a half-written
        decision."""
        self._epoch += 1
        self._kv_put(f"decision/{self._epoch}", {
            "epoch": self._epoch, "action": action,
            "generation": int(generation),
        })
        self._kv_put("epoch", {"epoch": self._epoch})
        if _metrics.enabled():
            _metrics.counter(
                "fleet_serving_decisions",
                help="fleet rollout decisions committed, by action",
                action=action,
            ).inc()
            _metrics.gauge(
                "fleet_serving_rollout_epoch",
                help="head of the fleet rollout decision log",
            ).set(self._epoch)
        _flight.record("fleet", what="rollout_decision", action=action,
                       generation=int(generation), epoch=self._epoch)
        self._apply_all()

    def _apply_all(self) -> None:
        for r in self._router.live_replicas(include_draining=True):
            self.apply(r)

    def apply(self, replica: FleetReplica) -> None:
        """Advance `replica` through the decision log, strictly in
        epoch order behind its ``applied_epoch`` fence."""
        head = self.head_epoch()
        while replica.applied_epoch < head:
            rec = self._kv_get(f"decision/{replica.applied_epoch + 1}")
            if rec is None or not self._apply_one(replica, rec):
                return
            replica.applied_epoch = int(rec["epoch"])

    def _apply_one(self, replica: FleetReplica,
                   rec: Dict[str, Any]) -> bool:
        action = rec["action"]
        gen = int(rec["generation"])
        eng = replica.engine
        if action in ("bootstrap", "canary"):
            sub = replica.subscriber
            if sub is None:
                return False
            if sub.generation < gen:
                sub.poll()
            if sub.generation == gen and sub.weights() is not None:
                arm = "stable" if action == "bootstrap" else "canary"
                eng.set_weights(sub.weights(), generation=gen, arm=arm)
                if action == "bootstrap":
                    replica.stable_generation = gen
                else:
                    replica.canary_generation = gen
                return True
            if sub.generation > gen:
                # the subscriber chain marched past this decision's
                # generation (GC'd); the arm stays un-installed here and
                # the router keeps this replica out of that arm's
                # candidates
                logger.warning(
                    "fleet: replica %s cannot install generation %d "
                    "(subscriber is at %d); skipping epoch %d",
                    replica.id, gen, sub.generation, rec["epoch"])
                return True
            return False  # not yet published this far: wait, fenced
        if action == "promote":
            if eng.arm_generation("canary") == gen:
                eng.promote_canary()
            replica.stable_generation = gen
            replica.canary_generation = None
            return True
        if action == "rollback":
            eng.retire_arm("canary")
            replica.canary_generation = None
            return True
        logger.warning("fleet: unknown rollout action %r", action)
        return True

    def catch_up(self, replica: FleetReplica) -> None:
        """A replica joining mid-history replays the decision log from
        epoch 0 (its fence starts there)."""
        self.apply(replica)

    # ---------------------------------------------------------- the loop

    def advance(self) -> None:
        """One coordinator turn (called from the router's pump): poll
        every replica's subscriber (running the per-replica health
        bridge), open a canary on the newest non-vetoed generation,
        apply any pending decisions, and judge the fleet-merged gate."""
        live = self._router.live_replicas(include_draining=True)
        for r in live:
            r.poll()
        gens = [int(r.subscriber.generation) for r in live
                if r.subscriber is not None
                and r.subscriber.weights() is not None]
        newest = max(gens, default=0)
        if newest > 0 and newest not in self._vetoed:
            if self._stable_gen is None:
                self._stable_gen = newest
                logger.info("fleet rollout: stable bootstrap at "
                            "generation %d", newest)
                self._commit("bootstrap", newest)
            elif (newest > self._stable_gen
                    and newest != self._canary_gen):
                # a newer candidate supersedes a half-evaluated canary,
                # exactly like the per-engine rollout
                self._canary_gen = newest
                self._router.reset_windows()
                logger.info(
                    "fleet rollout: canarying generation %d on %.0f%% "
                    "of traffic (stable %d)", newest,
                    100 * self.canary_fraction, self._stable_gen)
                self._emit("canary_started", newest)
                self._commit("canary", newest)
        self._apply_all()
        self._evaluate()
        self._record_state()

    def _evaluate(self) -> None:
        if self._canary_gen is None:
            return
        c = self._router.merged_window("canary",
                                       generation=self._canary_gen)
        s = self._router.merged_window("stable")
        verdict = judge_window(
            c, s, min_requests=self.min_canary_requests,
            max_error_rate=self.max_error_rate,
            max_latency_ratio=self.max_latency_ratio, slo=self._slo)
        if verdict is None:
            return
        action, why, objective = verdict
        gen = self._canary_gen
        if action == "promote":
            self._stable_gen = gen
            self._canary_gen = None
            self._router.reset_windows()
            logger.info("fleet rollout: promoted generation %d to "
                        "stable fleet-wide", gen)
            self._count_outcome("promoted")
            self._emit("promoted", gen)
            self._commit("promote", gen)
            return
        self._vetoed.add(gen)
        self._canary_gen = None
        self._router.reset_windows()
        if objective is not None:
            _health.record_slo_burn(
                objective, f"canary generation {gen} (fleet)")
        logger.warning(
            "fleet rollout: generation %d rolled back to %s "
            "fleet-wide (%s)", gen, self._stable_gen, why)
        self._count_outcome("rolled_back")
        self._emit("rolled_back", gen)
        self._commit("rollback", gen)

    # ---------------------------------------------------------- plumbing

    def _count_outcome(self, outcome: str) -> None:
        if _metrics.enabled():
            _metrics.counter(
                "fleet_serving_rollouts",
                help="fleet-wide canary evaluations concluded, by "
                     "outcome",
                outcome=outcome,
            ).inc()

    def _emit(self, event: str, generation: int) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(event, generation)
        except Exception as e:  # noqa: BLE001 - observer, best-effort
            logger.debug("fleet on_event callback failed: %s", e)

    def _record_state(self) -> None:
        if not _metrics.enabled():
            return
        _metrics.gauge(
            "fleet_serving_rollout_state",
            help="0 = fleet serving stable only, 1 = canary in flight",
        ).set(0 if self._canary_gen is None else 1)
        if self._stable_gen is not None:
            _metrics.gauge(
                "fleet_serving_stable_generation",
                help="generation the fleet's stable arm serves",
            ).set(self._stable_gen)
        _metrics.gauge(
            "fleet_serving_canary_generation",
            help="generation under fleet-wide canary (-1 = none)",
        ).set(-1 if self._canary_gen is None else self._canary_gen)
