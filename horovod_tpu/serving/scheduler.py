"""Continuous-batching request scheduler and paged KV-cache allocator.

The scheduling model is Orca's iteration-level scheduling (Yu et al.,
OSDI '22) over vLLM-style paged memory (Kwon et al., SOSP '23), sized for
determinism rather than peak throughput:

- Requests queue FIFO; a full queue rejects at :meth:`submit` — the
  admission-control backpressure the ``request_burst`` chaos charge
  exercises.
- A sequence joins the batch at any iteration boundary: admission takes a
  free batch **slot** plus a *conservative* page reservation — every page
  the sequence could ever need (``ceil((prompt + max_new) / page_size)``)
  is claimed up front, so an admitted sequence can never be evicted
  mid-flight and the page pool can never over-commit. When the head of
  the queue does not fit, admission stops (head-of-line, deterministic)
  and the queue depth is the backpressure signal.
- A finished sequence frees its slot and pages at the same boundary it
  finishes — the next admission sees them immediately.

Page 0 of the pool is the **trash page**: batch rows that are inactive in
a given compiled step (empty slots, rows in the other rollout arm, the
masked tail of a ragged prefill chunk) route their cache writes there via
an all-zero page table, keeping every shape static without a write mask.
Nothing ever reads it — the causal mask in
:func:`horovod_tpu.ops.flash_attention.decode_attention` makes positions
past a row's frontier unobservable.

stdlib + numpy only; the engine owns everything jax.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.observability import flight as _flight
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import reqtrace as _reqtrace

__all__ = ["QueueFull", "Request", "Sequence",
           "ContinuousBatchingScheduler", "DEFAULT_BACKPRESSURE_TPOT"]


class QueueFull(RuntimeError):
    """The request queue is at ``max_queue`` — admission control rejected
    the request instead of growing without bound. Serve-side backpressure:
    the caller sheds load or retries later.

    ``retry_after_s`` is a deterministic backoff hint (queue depth ×
    the windowed TPOT median — roughly how long the backlog ahead of
    the caller takes to move) so callers pace their retries
    proportionally instead of hammering a saturated engine."""

    def __init__(self, msg: str = "",
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


# TPOT stand-in for the backpressure hint before any token has decoded
# (a cold engine has no window yet but a full queue still needs a hint)
DEFAULT_BACKPRESSURE_TPOT = 0.02


class Request:
    """One generation request.

    - `rid`: caller's id (routing hash + metrics correlation).
    - `prompt`: 1-D int tokens.
    - `max_new_tokens`: tokens to generate (the sequence finishes earlier
      on `eos_token` when the engine has one).
    - `temperature`: 0 = greedy argmax; > 0 samples ``logits/temperature``
      with a deterministic per-request PRNG seeded from `rid`.
    - `arm`: rollout arm serving this request (``"stable"`` unless a
      :class:`~horovod_tpu.serving.rollout.GenerationRollout` routed it
      to the canary).
    """

    def __init__(self, rid, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, arm: str = "stable"):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must carry at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.arm = arm
        self.submitted_at = time.monotonic()
        # filled in when the sequence finishes
        self.tokens: Optional[np.ndarray] = None  # prompt + generated
        self.generated: Optional[List[int]] = None
        self.error: Optional[str] = None
        self.finished_at: Optional[float] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def latency_seconds(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class Sequence:
    """In-flight decoding state for one admitted request.

    ``arm`` is the engine weight arm this sequence decodes against —
    pinned at admission and only ever moved to an arm holding the SAME
    params (promotion relabels, drain labels): a sequence must never
    change weights mid-decode, its KV cache was built under them.
    ``req.arm`` stays the user-facing label (metrics, routing)."""

    def __init__(self, req: Request, slot: int, pages: List[int]):
        self.req = req
        self.arm = req.arm
        self.slot = slot
        self.pages = pages
        self.prompt_len = int(req.prompt.size)
        self.done_prompt = 0        # prompt tokens written to the cache
        self.generated: List[int] = []
        self.last_token: Optional[int] = None  # sampled, not yet cached
        self._rng: Optional[np.random.RandomState] = None

    @property
    def length(self) -> int:
        """Tokens currently written to the kv cache."""
        if self.done_prompt < self.prompt_len:
            return self.done_prompt
        # prompt + every generated token except the freshly sampled one
        return self.prompt_len + max(0, len(self.generated) - 1)

    @property
    def prefilling(self) -> bool:
        return self.done_prompt < self.prompt_len

    def sample(self, logits: np.ndarray) -> int:
        """Greedy argmax or temperature sampling of one next token from a
        ``[vocab]`` logits row — deterministic per request (the PRNG seeds
        from a crc32 of `rid`, like the rollout router: Python's built-in
        ``hash`` is salted per process, which would break cross-process /
        cross-restart replayability)."""
        if self.req.temperature <= 0.0:
            return int(np.argmax(logits))
        if self._rng is None:
            import zlib

            self._rng = np.random.RandomState(
                zlib.crc32(str(self.req.rid).encode()) or 1)
        z = logits.astype(np.float64) / self.req.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(p.size, p=p))


class _CancelShim:
    """Minimal Sequence stand-in for cancelling a never-admitted
    request — the reqtrace finish path reads only ``.req``."""

    __slots__ = ("req",)

    def __init__(self, req: Request):
        self.req = req


class ContinuousBatchingScheduler:
    """Slots, queue, and the page-pool free list.

    All methods are lock-safe: :meth:`submit` may be called from serving
    threads while the engine loop runs :meth:`admit` / :meth:`finish`.
    """

    def __init__(self, *, num_pages: int, page_size: int, max_batch: int,
                 pages_per_seq: int, max_queue: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the trash page), "
                f"got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_batch = int(max_batch)
        self.pages_per_seq = int(pages_per_seq)
        self.max_queue = int(max_queue)
        self._lock = threading.Lock()
        # page 0 reserved as the trash page for masked writes
        self._free_pages: List[int] = list(range(1, self.num_pages))
        self._queue: deque = deque()
        self._slots: List[Optional[Sequence]] = [None] * self.max_batch

    # -------------------------------------------------------------- intake

    def submit(self, req: Request) -> None:
        """Queue a request; raises :class:`QueueFull` past ``max_queue``
        (counted as ``serving_admission_rejected{reason=queue_full}``) and
        rejects prompts that can never fit the per-sequence page budget."""
        pages_needed = self._pages_for(req)
        if pages_needed > self.pages_per_seq:
            self._reject(req, "too_long",
                         f"needs {pages_needed} pages, per-sequence "
                         f"capacity is {self.pages_per_seq}")
            raise ValueError(
                f"request {req.rid!r} needs {pages_needed} pages "
                f"({req.prompt.size} prompt + {req.max_new_tokens} new "
                f"tokens), capacity is {self.pages_per_seq} pages of "
                f"{self.page_size}")
        with self._lock:
            full = len(self._queue) >= self.max_queue
            if not full:
                self._queue.append(req)
        if full:
            # outside the lock: the reject path records metrics + a
            # flight event (periodic sidecar I/O) — under overload, when
            # rejections spike, that must not stall concurrent
            # submit/admit/finish callers
            hint = self.backpressure_hint()
            self._reject(req, "queue_full",
                         f"queue at max_queue={self.max_queue}; retry "
                         f"after ~{hint:.3f}s")
            raise QueueFull(
                f"request queue full ({self.max_queue}); shed load or "
                f"retry in ~{hint:.3f}s", retry_after_s=hint)
        # per-request lifecycle opens here (trace lane, flight
        # req_begin, the queue-wait clock) — outside the lock, like the
        # reject path
        _reqtrace.on_enqueue(req)
        if _metrics.enabled():
            _metrics.gauge(
                "serving_queue_depth",
                help="requests queued awaiting a slot + page reservation",
            ).set(self.queue_depth())

    def _reject(self, req: Request, reason: str, detail: str) -> None:
        req.error = f"rejected: {detail}"
        req.finished_at = time.monotonic()
        req._done.set()
        # flight ring: shed load is an admission decision the post-mortem
        # record keeps (was the engine rejecting before it died?)
        _flight.record("serve", what="reject", reason=reason)
        _reqtrace.on_reject(req, reason)
        if _metrics.enabled():
            _metrics.counter(
                "serving_admission_rejected",
                help="requests refused by admission control",
                reason=reason,
            ).inc()

    def _pages_for(self, req: Request) -> int:
        total = req.prompt.size + req.max_new_tokens
        return -(-int(total) // self.page_size)

    def backpressure_hint(self) -> float:
        """Deterministic retry-after estimate for a rejected caller:
        queue depth × the windowed TPOT median (how long the backlog
        ahead will roughly take to move one decode step each). Also
        published as the ``fleet_backpressure_hint_seconds`` gauge so
        the router / dashboards see the same number the caller got."""
        tpot = _reqtrace.recent_tpot(DEFAULT_BACKPRESSURE_TPOT)
        hint = max(1, self.queue_depth()) * float(tpot)
        if _metrics.enabled():
            _metrics.gauge(
                "fleet_backpressure_hint_seconds",
                help="retry-after hint handed to rejected callers "
                     "(queue depth x windowed TPOT median)",
            ).set(hint)
        return hint

    def cancel(self, req: Request, reason: str = "cancelled") -> bool:
        """Withdraw a request at an iteration boundary: queued requests
        leave the queue outright; an in-flight sequence retires with
        `reason` as its error, freeing its slot and pages. Returns False
        when the request is unknown or already finished. The reason is
        normalized to start with ``"cancelled"`` — reqtrace keeps such
        completions out of the arm windows and the error-rate SLO (a
        hedge loser withdrawn by the fleet router was never a served
        outcome). Callers must only cancel between engine steps: an
        in-flight retire mid-pass would invalidate the pass's captured
        batch rows."""
        if not reason.startswith("cancelled"):
            reason = f"cancelled: {reason}"
        with self._lock:
            queued = req in self._queue
            if queued:
                self._queue.remove(req)
                seq = None
            else:
                seq = next((s for s in self._slots
                            if s is not None and s.req is req), None)
        if queued:
            req.generated = []
            req.tokens = np.asarray(req.prompt, np.int32)
            req.error = reason
            req.finished_at = time.monotonic()
            req._done.set()
            if _metrics.enabled():
                _metrics.counter(
                    "serving_requests",
                    help="generation requests completed, by rollout arm "
                         "and outcome",
                    arm=req.arm, outcome="cancelled",
                ).inc()
            # close the reqtrace lifecycle without a Sequence — only
            # ``seq.req`` is read on the finish path
            _reqtrace.on_finish(_CancelShim(req), error=reason)
            self._record_gauges()
            return True
        if seq is None or req.done:
            return False
        self.finish(seq, error=reason)
        return True

    # ----------------------------------------------------------- admission

    def admit(self) -> List[Sequence]:
        """Move queued requests into free slots while their full page
        reservation fits — head-of-line order, so admission is
        deterministic and a too-big head request backpressures the queue
        rather than being overtaken."""
        admitted: List[Sequence] = []
        with self._lock:
            while self._queue:
                slot = next(
                    (i for i, s in enumerate(self._slots) if s is None),
                    None)
                if slot is None:
                    break
                req = self._queue[0]
                need = self._pages_for(req)
                if need > len(self._free_pages):
                    break  # page-pool backpressure
                self._queue.popleft()
                pages = [self._free_pages.pop(0) for _ in range(need)]
                seq = Sequence(req, slot, pages)
                self._slots[slot] = seq
                admitted.append(seq)
        if admitted:
            _flight.record(
                "serve", what="admit", n=len(admitted),
                queue=self.queue_depth(),
            )
            for seq in admitted:
                _reqtrace.on_admit(seq)
            if _metrics.enabled():
                _metrics.counter(
                    "serving_sequences_admitted",
                    help="sequences that joined the continuous batch",
                ).inc(len(admitted))
        self._record_gauges()
        return admitted

    def finish(self, seq: Sequence, *, error: Optional[str] = None) -> None:
        """Retire a sequence at an iteration boundary: result (or error)
        onto the request, slot and pages freed immediately."""
        req = seq.req
        req.generated = list(seq.generated)
        req.tokens = np.concatenate(
            [req.prompt, np.asarray(seq.generated, np.int32)])
        req.error = error
        req.finished_at = time.monotonic()
        with self._lock:
            self._slots[seq.slot] = None
            # keep the free list sorted so page assignment is a pure
            # function of the admission order (deterministic replays)
            self._free_pages = sorted(self._free_pages + seq.pages)
        req._done.set()
        if _metrics.enabled():
            _metrics.counter(
                "serving_requests",
                help="generation requests completed, by rollout arm and "
                     "outcome",
                arm=req.arm,
                outcome="cancelled" if error
                and error.startswith("cancelled")
                else ("error" if error else "ok"),
            ).inc()
        # the one completion observation path: reqtrace closes the
        # request's span lifecycle, lands the e2e/TTFT/TPOT histograms
        # (including the old serving_request_latency_seconds alias), and
        # appends to the per-arm window the rollout/SLO gates read
        _reqtrace.on_finish(seq, error=error)
        self._record_gauges()

    # -------------------------------------------------------------- views

    def active(self, arm: Optional[str] = None) -> List[Sequence]:
        with self._lock:
            seqs = [s for s in self._slots if s is not None]
        if arm is not None:
            seqs = [s for s in seqs if s.arm == arm]
        return seqs

    def arms_active(self) -> List[str]:
        seen: Dict[str, bool] = {}
        for s in self.active():
            seen.setdefault(s.arm, True)
        return list(seen)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def relabel_arm(self, src: str, dst: str) -> None:
        """Move every queued request and in-flight sequence from arm `src`
        to `dst`. Legal ONLY when `dst` holds the same params as `src`
        (promotion: identical weights under a new label) — a sequence must
        never change weights mid-decode."""
        moved: List[Request] = []
        with self._lock:
            for req in self._queue:
                if req.arm == src:
                    req.arm = dst
                    moved.append(req)
            for s in self._slots:
                if s is not None and s.arm == src:
                    s.arm = dst
                    s.req.arm = dst
                    moved.append(s.req)
        for req in moved:
            _reqtrace.on_relabel(req, src, dst)

    def relabel_queued_only(self, src: str, dst: str) -> None:
        """Re-route queued `src` requests to `dst` without touching
        in-flight sequences (the rollback path: admitted canary work
        drains on its own weights)."""
        moved: List[Request] = []
        with self._lock:
            for req in self._queue:
                if req.arm == src:
                    req.arm = dst
                    moved.append(req)
        for req in moved:
            _reqtrace.on_relabel(req, src, dst)

    def move_active_to_drain(self, src: str, drain_label: str) -> int:
        """Re-bind in-flight `src` sequences to `drain_label` — the SAME
        params parked under a private label so they finish coherently
        while `src` is handed to a new weight generation. ``req.arm`` (the
        metrics/routing label) is untouched. Returns how many moved."""
        n = 0
        with self._lock:
            for s in self._slots:
                if s is not None and s.arm == src:
                    s.arm = drain_label
                    n += 1
        return n

    def pages_in_use(self) -> int:
        with self._lock:
            return (self.num_pages - 1) - len(self._free_pages)

    def free_page_count(self) -> int:
        with self._lock:
            return len(self._free_pages)

    def idle(self) -> bool:
        with self._lock:
            return not self._queue and all(
                s is None for s in self._slots)

    def page_table_rows(self) -> np.ndarray:
        """``[max_batch, pages_per_seq]`` int32 page table for the current
        batch composition: admitted rows get their pages (tail-padded with
        the trash page), empty slots are all-trash."""
        table = np.zeros((self.max_batch, self.pages_per_seq), np.int32)
        with self._lock:
            for i, s in enumerate(self._slots):
                if s is not None:
                    table[i, :len(s.pages)] = np.asarray(s.pages, np.int32)
        return table

    def _record_gauges(self) -> None:
        if not _metrics.enabled():
            return
        _metrics.gauge(
            "serving_queue_depth",
            help="requests queued awaiting a slot + page reservation",
        ).set(self.queue_depth())
        _metrics.gauge(
            "serving_active_sequences",
            help="sequences currently holding a batch slot",
        ).set(len(self.active()))
        _metrics.gauge(
            "serving_pages_in_use",
            help="kv-cache pages currently reserved by admitted sequences",
        ).set(self.pages_in_use())
        _metrics.gauge(
            "serving_page_pool_pages",
            help="allocatable kv-cache pages in the pool (excludes the "
                 "trash page)",
        ).set(self.num_pages - 1)
