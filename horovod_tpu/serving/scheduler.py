"""Continuous-batching request scheduler and paged KV-cache allocator.

The scheduling model is Orca's iteration-level scheduling (Yu et al.,
OSDI '22) over vLLM-style paged memory (Kwon et al., SOSP '23), sized for
determinism rather than peak throughput:

- Requests queue FIFO; a full queue rejects at :meth:`submit` — the
  admission-control backpressure the ``request_burst`` chaos charge
  exercises.
- A sequence joins the batch at any iteration boundary: admission takes a
  free batch **slot** plus a *conservative* page reservation — every page
  the sequence could ever need (``ceil((prompt + max_new) / page_size)``)
  is claimed up front, so an admitted sequence can never be evicted
  mid-flight and the page pool can never over-commit. When the head of
  the queue does not fit, admission stops (head-of-line, deterministic)
  and the queue depth is the backpressure signal.
- A finished sequence frees its slot and pages at the same boundary it
  finishes — the next admission sees them immediately.

Page 0 of the pool is the **trash page**: batch rows that are inactive in
a given compiled step (empty slots, rows in the other rollout arm, the
masked tail of a ragged prefill chunk) route their cache writes there via
an all-zero page table, keeping every shape static without a write mask.
Nothing ever reads it — the causal mask in
:func:`horovod_tpu.ops.flash_attention.decode_attention` makes positions
past a row's frontier unobservable.

**Automatic prefix caching** rides the same pool: a finished sequence's
*full prompt pages* (page index < ``prompt_len // page_size`` — the only
pages holding pure prompt KV, no pad tail and no decode writes) enter a
refcounted index keyed by chained block hashes, namespaced by the weight
generation that wrote them. Admission walks the new prompt's chain and
**aliases** every resident page it matches into the sequence's page
table (page tables are a pure gather, so N sequences can read one page),
reserving and prefilling only the non-shared tail. Sharing is
whole-page: a divergent continuation always lands in the sequence's own
freshly reserved tail pages, so copy-on-write never has to copy — a
shared page is never written by anyone. Eviction frees only
refcount-0 pages, least-recently-released first, and only when
admission actually runs short. The hit is rounded down to a multiple of
``lcm(page_size, prefill_chunk)`` (chunk starts must stay multiples of
``prefill_chunk`` so a chunk's clamped pad tail can never fold back
into a real page) and capped strictly below the prompt end (the last
prompt token always prefills — it produces the first-token logits), so
served tokens are BIT-identical to the uncached engine.

stdlib + numpy only; the engine owns everything jax.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque
from math import gcd
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.observability import flight as _flight
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import reqtrace as _reqtrace

__all__ = ["QueueFull", "Request", "Sequence", "PrefixCache",
           "prefix_digests", "ContinuousBatchingScheduler",
           "DEFAULT_BACKPRESSURE_TPOT"]


class QueueFull(RuntimeError):
    """The request queue is at ``max_queue`` — admission control rejected
    the request instead of growing without bound. Serve-side backpressure:
    the caller sheds load or retries later.

    ``retry_after_s`` is a deterministic backoff hint (queue depth ×
    the windowed TPOT median — roughly how long the backlog ahead of
    the caller takes to move) so callers pace their retries
    proportionally instead of hammering a saturated engine."""

    def __init__(self, msg: str = "",
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


# TPOT stand-in for the backpressure hint before any token has decoded
# (a cold engine has no window yet but a full queue still needs a hint)
DEFAULT_BACKPRESSURE_TPOT = 0.02


def prefix_digests(prompt, page_size: int,
                   limit: Optional[int] = None) -> List[str]:
    """Chained block digests for every FULL ``page_size`` block of
    `prompt` — digest *i* commits to all tokens up to and including
    block *i*, so matching digest *i* proves the whole prefix matches.

    Content-only (no weight generation): the fleet router uses these to
    score prefix locality against a replica's advertised summary without
    knowing which generation the replica serves; the cache index adds
    its own generation namespace on top."""
    toks = np.asarray(prompt, np.int32).reshape(-1)
    nblocks = int(toks.size) // int(page_size)
    if limit is not None:
        nblocks = min(nblocks, int(limit))
    out: List[str] = []
    h = b"hvd-prefix-v1"
    for i in range(nblocks):
        block = toks[i * page_size:(i + 1) * page_size]
        h = hashlib.blake2b(
            h + block.tobytes(), digest_size=16).digest()
        out.append(h.hex())
    return out


class PrefixCache:
    """Refcounted prefix-page index over the paged KV pool.

    Pure bookkeeping (the pages themselves live in the engine's pool):
    maps ``(namespace, chain-digest) → page`` for pages whose KV is a
    verbatim full prompt block written under weight generation
    ``namespace``. A page is in exactly one of three states:

    - **shared** — refcount ≥ 1: aliased into one or more live
      sequences' page tables. Never evictable, never written.
    - **resident** — refcount 0 but still indexed: a future admission
      may alias it. Sits in the LRU (ordered by release recency) and is
      reclaimed only when admission runs short of free pages.
    - gone — evicted back to the scheduler's free list.

    Callers hold the scheduler lock; this class adds no locking."""

    def __init__(self, page_size: int, prefill_chunk: int):
        self.page_size = int(page_size)
        self.prefill_chunk = int(prefill_chunk)
        #: hit granularity: chunk starts must remain multiples of
        #: prefill_chunk (pad-tail clamp invariant), page ownership is
        #: whole pages — so hits advance in lcm(page, chunk) tokens
        self.align_tokens = (self.page_size * self.prefill_chunk
                            // gcd(self.page_size, self.prefill_chunk))
        self.align_pages = self.align_tokens // self.page_size
        self._by_key: Dict[Tuple[int, str], int] = {}
        self._key_of: Dict[int, Tuple[int, str]] = {}
        self._ref: Dict[int, int] = {}
        self._lru: "OrderedDict[int, bool]" = OrderedDict()

    # ------------------------------------------------------------ queries

    def lookup(self, namespace: int, digests: List[str]) -> List[int]:
        """Longest resident run of chained blocks, as pool pages (NOT
        yet acquired — callers :meth:`acquire` before any eviction can
        run, or the hit itself could be reclaimed)."""
        pages: List[int] = []
        for d in digests:
            p = self._by_key.get((int(namespace), d))
            if p is None:
                break
            pages.append(p)
        return pages

    def max_hit_pages(self, prompt_len: int) -> int:
        """Largest usable hit for a prompt: a multiple of the alignment
        run, strictly below the prompt end (the final prompt token must
        prefill to produce the first-token logits)."""
        runs = (int(prompt_len) - 1) // self.align_tokens
        return runs * self.align_pages

    def usable_hit(self, namespace: int, digests: List[str],
                   prompt_len: int) -> List[int]:
        run = self.lookup(namespace, digests)
        n = min(len(run), self.max_hit_pages(prompt_len))
        n -= n % self.align_pages
        return run[:n]

    # --------------------------------------------------------- refcounts

    def acquire(self, pages: List[int]) -> None:
        for p in pages:
            self._ref[p] = self._ref.get(p, 0) + 1
            self._lru.pop(p, None)

    def release(self, pages: List[int]) -> None:
        for p in pages:
            n = self._ref.get(p, 0) - 1
            if n > 0:
                self._ref[p] = n
                continue
            self._ref.pop(p, None)
            if p in self._key_of:
                # most-recently released goes to the LRU tail
                self._lru[p] = True
                self._lru.move_to_end(p)

    def insert(self, namespace: int, digest: str, page: int) -> bool:
        """Index `page` as the block behind `digest`; False when the
        block is already resident (the caller keeps ownership of its
        duplicate copy and frees it)."""
        key = (int(namespace), digest)
        if key in self._by_key:
            return False
        self._by_key[key] = page
        self._key_of[page] = key
        self._lru[page] = True
        return True

    # ---------------------------------------------------------- eviction

    def evictable(self) -> int:
        return len(self._lru)

    def evict(self, n: int) -> List[int]:
        """Reclaim up to `n` refcount-0 pages, least-recently-released
        first. Deterministic: the LRU order is a pure function of the
        admit/finish sequence."""
        out: List[int] = []
        while self._lru and len(out) < n:
            p, _ = self._lru.popitem(last=False)
            self._by_key.pop(self._key_of.pop(p), None)
            out.append(p)
        return out

    # ------------------------------------------------------------- views

    def resident_pages(self) -> int:
        """Indexed pages (shared + idle) — pool pages the cache holds."""
        return len(self._key_of)

    def shared_page_count(self) -> int:
        """Indexed pages aliased by at least one live sequence."""
        return sum(1 for p in self._ref if p in self._key_of)

    def block_summary(self, limit: int = 64) -> List[str]:
        """Content digests of resident blocks (generation-free), for
        the fleet status blob. Sorted for deterministic publication."""
        digs = sorted(k[1] for k in self._by_key)
        return digs[:int(limit)]


class Request:
    """One generation request.

    - `rid`: caller's id (routing hash + metrics correlation).
    - `prompt`: 1-D int tokens.
    - `max_new_tokens`: tokens to generate (the sequence finishes earlier
      on `eos_token` when the engine has one).
    - `temperature`: 0 = greedy argmax; > 0 samples ``logits/temperature``
      with a deterministic per-request PRNG seeded from `rid`.
    - `arm`: rollout arm serving this request (``"stable"`` unless a
      :class:`~horovod_tpu.serving.rollout.GenerationRollout` routed it
      to the canary).
    """

    def __init__(self, rid, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, arm: str = "stable"):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must carry at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.arm = arm
        self.submitted_at = time.monotonic()
        # filled in when the sequence finishes
        self.tokens: Optional[np.ndarray] = None  # prompt + generated
        self.generated: Optional[List[int]] = None
        self.error: Optional[str] = None
        self.finished_at: Optional[float] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def latency_seconds(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class Sequence:
    """In-flight decoding state for one admitted request.

    ``arm`` is the engine weight arm this sequence decodes against —
    pinned at admission and only ever moved to an arm holding the SAME
    params (promotion relabels, drain labels): a sequence must never
    change weights mid-decode, its KV cache was built under them.
    ``req.arm`` stays the user-facing label (metrics, routing)."""

    def __init__(self, req: Request, slot: int, pages: List[int]):
        self.req = req
        self.arm = req.arm
        self.slot = slot
        self.pages = pages
        self.prompt_len = int(req.prompt.size)
        self.done_prompt = 0        # prefill tokens written to the cache
        self.generated: List[int] = []
        self.last_token: Optional[int] = None  # sampled, not yet cached
        self._rng: Optional[np.random.RandomState] = None
        # --- prefix-cache state ---
        #: leading pages of ``pages`` aliased from the prefix cache
        #: (never written by this sequence; decref'd at finish)
        self.shared_count = 0
        #: weight-generation namespace captured at admission (None =
        #: caching off for this sequence)
        self.prefix_ns: Optional[int] = None
        #: chained digests of the prompt's full blocks (insert keys)
        self.prefix_chain: Optional[List[str]] = None
        #: what the prefill passes write — the prompt, unless a forced
        #: cache eviction restarted the sequence (then prompt + every
        #: generated-but-uncached token gets rewritten, bit-identically)
        self.prefill_src: np.ndarray = req.prompt
        self.prefill_len: int = self.prompt_len

    @property
    def length(self) -> int:
        """Tokens currently written to the kv cache."""
        if self.done_prompt < self.prefill_len:
            return self.done_prompt
        # prompt + every generated token except the freshly sampled one
        return self.prompt_len + max(0, len(self.generated) - 1)

    @property
    def prefilling(self) -> bool:
        return self.done_prompt < self.prefill_len

    def restart_prefill(self) -> None:
        """Rebuild this sequence's whole KV from position 0 (the forced
        cache-eviction drill evicted pages it was aliasing): replay the
        prompt plus every generated token that already had KV written.
        ``last_token`` (sampled, not yet cached) survives, so decoding
        resumes exactly where it stopped — bit-identically, since the
        replayed writes are the same tokens at the same positions."""
        tail = np.asarray(self.generated[:-1] if self.generated else [],
                          np.int32)
        self.prefill_src = np.concatenate([self.req.prompt, tail])
        self.prefill_len = int(self.prefill_src.size)
        self.done_prompt = 0

    def sample(self, logits: np.ndarray) -> int:
        """Greedy argmax or temperature sampling of one next token from a
        ``[vocab]`` logits row — deterministic per request (the PRNG seeds
        from a crc32 of `rid`, like the rollout router: Python's built-in
        ``hash`` is salted per process, which would break cross-process /
        cross-restart replayability)."""
        if self.req.temperature <= 0.0:
            return int(np.argmax(logits))
        if self._rng is None:
            import zlib

            self._rng = np.random.RandomState(
                zlib.crc32(str(self.req.rid).encode()) or 1)
        z = logits.astype(np.float64) / self.req.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(p.size, p=p))


class _CancelShim:
    """Minimal Sequence stand-in for cancelling a never-admitted
    request — the reqtrace finish path reads only ``.req``."""

    __slots__ = ("req",)

    def __init__(self, req: Request):
        self.req = req


class ContinuousBatchingScheduler:
    """Slots, queue, and the page-pool free list.

    All methods are lock-safe: :meth:`submit` may be called from serving
    threads while the engine loop runs :meth:`admit` / :meth:`finish`.
    """

    def __init__(self, *, num_pages: int, page_size: int, max_batch: int,
                 pages_per_seq: int, max_queue: int,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = False,
                 namespace_of: Optional[Callable[[str],
                                                 Optional[int]]] = None):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the trash page), "
                f"got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_batch = int(max_batch)
        self.pages_per_seq = int(pages_per_seq)
        self.max_queue = int(max_queue)
        self._lock = threading.Lock()
        # page 0 reserved as the trash page for masked writes
        self._free_pages: List[int] = list(range(1, self.num_pages))
        self._queue: deque = deque()
        self._slots: List[Optional[Sequence]] = [None] * self.max_batch
        #: prefix cache (None = off): hits alias resident pages at
        #: admission, full prompt pages are indexed at finish
        self._prefix: Optional[PrefixCache] = PrefixCache(
            self.page_size,
            prefill_chunk if prefill_chunk is not None
            else self.page_size) if prefix_cache else None
        #: arm → weight-generation namespace (the engine's resolver);
        #: returning None disables caching for that request
        self._namespace_of = namespace_of if namespace_of is not None \
            else (lambda arm: 0)

    # -------------------------------------------------------------- intake

    def submit(self, req: Request) -> None:
        """Queue a request; raises :class:`QueueFull` past ``max_queue``
        (counted as ``serving_admission_rejected{reason=queue_full}``) and
        rejects prompts that can never fit the per-sequence page budget."""
        pages_needed = self._pages_for(req)
        if pages_needed > self.pages_per_seq:
            self._reject(req, "too_long",
                         f"needs {pages_needed} pages, per-sequence "
                         f"capacity is {self.pages_per_seq}")
            raise ValueError(
                f"request {req.rid!r} needs {pages_needed} pages "
                f"({req.prompt.size} prompt + {req.max_new_tokens} new "
                f"tokens), capacity is {self.pages_per_seq} pages of "
                f"{self.page_size}")
        with self._lock:
            full = len(self._queue) >= self.max_queue
            if not full:
                self._queue.append(req)
        if full:
            # outside the lock: the reject path records metrics + a
            # flight event (periodic sidecar I/O) — under overload, when
            # rejections spike, that must not stall concurrent
            # submit/admit/finish callers
            hint = self.backpressure_hint(req)
            self._reject(req, "queue_full",
                         f"queue at max_queue={self.max_queue}; retry "
                         f"after ~{hint:.3f}s")
            raise QueueFull(
                f"request queue full ({self.max_queue}); shed load or "
                f"retry in ~{hint:.3f}s", retry_after_s=hint)
        # per-request lifecycle opens here (trace lane, flight
        # req_begin, the queue-wait clock) — outside the lock, like the
        # reject path
        _reqtrace.on_enqueue(req)
        if _metrics.enabled():
            _metrics.gauge(
                "serving_queue_depth",
                help="requests queued awaiting a slot + page reservation",
            ).set(self.queue_depth())

    def _reject(self, req: Request, reason: str, detail: str) -> None:
        req.error = f"rejected: {detail}"
        req.finished_at = time.monotonic()
        req._done.set()
        # flight ring: shed load is an admission decision the post-mortem
        # record keeps (was the engine rejecting before it died?)
        _flight.record("serve", what="reject", reason=reason)
        _reqtrace.on_reject(req, reason)
        if _metrics.enabled():
            _metrics.counter(
                "serving_admission_rejected",
                help="requests refused by admission control",
                reason=reason,
            ).inc()

    def _pages_for(self, req: Request) -> int:
        total = req.prompt.size + req.max_new_tokens
        return -(-int(total) // self.page_size)

    def backpressure_hint(self, req: Optional[Request] = None) -> float:
        """Deterministic retry-after estimate for a rejected caller:
        queue depth × the windowed TPOT median (how long the backlog
        ahead will roughly take to move one decode step each). When
        `req` is given and the prefix cache would credit part of its
        reservation, the hint scales by the post-credit fraction — a
        mostly-cached prompt frees up to admit much sooner than its
        worst-case reservation suggests (floored at one TPOT: it still
        needs a slot). Also published as the
        ``fleet_backpressure_hint_seconds`` gauge so the router /
        dashboards see the same number the caller got."""
        tpot = _reqtrace.recent_tpot(DEFAULT_BACKPRESSURE_TPOT)
        hint = max(1, self.queue_depth()) * float(tpot)
        if req is not None and self._prefix is not None:
            ns = self._namespace_of(req.arm)
            if ns is not None:
                worst = self._pages_for(req)
                with self._lock:
                    hit = len(self._prefix.usable_hit(
                        ns, prefix_digests(req.prompt, self.page_size),
                        int(req.prompt.size)))
                hint = max(hint * (worst - hit) / max(1, worst),
                           float(tpot))
        if _metrics.enabled():
            _metrics.gauge(
                "fleet_backpressure_hint_seconds",
                help="retry-after hint handed to rejected callers "
                     "(queue depth x windowed TPOT median)",
            ).set(hint)
        return hint

    def cancel(self, req: Request, reason: str = "cancelled") -> bool:
        """Withdraw a request at an iteration boundary: queued requests
        leave the queue outright; an in-flight sequence retires with
        `reason` as its error, freeing its slot and pages. Returns False
        when the request is unknown or already finished. The reason is
        normalized to start with ``"cancelled"`` — reqtrace keeps such
        completions out of the arm windows and the error-rate SLO (a
        hedge loser withdrawn by the fleet router was never a served
        outcome). Callers must only cancel between engine steps: an
        in-flight retire mid-pass would invalidate the pass's captured
        batch rows."""
        if not reason.startswith("cancelled"):
            reason = f"cancelled: {reason}"
        with self._lock:
            queued = req in self._queue
            if queued:
                self._queue.remove(req)
                seq = None
            else:
                seq = next((s for s in self._slots
                            if s is not None and s.req is req), None)
        if queued:
            req.generated = []
            req.tokens = np.asarray(req.prompt, np.int32)
            req.error = reason
            req.finished_at = time.monotonic()
            req._done.set()
            if _metrics.enabled():
                _metrics.counter(
                    "serving_requests",
                    help="generation requests completed, by rollout arm "
                         "and outcome",
                    arm=req.arm, outcome="cancelled",
                ).inc()
            # close the reqtrace lifecycle without a Sequence — only
            # ``seq.req`` is read on the finish path
            _reqtrace.on_finish(_CancelShim(req), error=reason)
            self._record_gauges()
            return True
        if seq is None or req.done:
            return False
        self.finish(seq, error=reason)
        return True

    # ----------------------------------------------------------- admission

    def admit(self) -> List[Sequence]:
        """Move queued requests into free slots while their full page
        reservation fits — head-of-line order, so admission is
        deterministic and a too-big head request backpressures the queue
        rather than being overtaken.

        With the prefix cache on, the head request's chained block
        digests are matched against the index first: matched pages are
        **aliased** (refcount bump, ``done_prompt`` pre-advanced past
        them) and the reservation only covers the non-shared tail — a
        fully-cached prompt admits with a near-zero page bill instead of
        backpressuring at high occupancy. When the tail still does not
        fit, refcount-0 resident pages are LRU-evicted on demand before
        giving up."""
        admitted: List[Sequence] = []
        evicted = 0
        hits = 0
        misses = 0
        with self._lock:
            while self._queue:
                slot = next(
                    (i for i, s in enumerate(self._slots) if s is None),
                    None)
                if slot is None:
                    break
                req = self._queue[0]
                worst = self._pages_for(req)
                hit_pages: List[int] = []
                chain: Optional[List[str]] = None
                ns: Optional[int] = None
                if self._prefix is not None:
                    ns = self._namespace_of(req.arm)
                    if ns is not None:
                        chain = prefix_digests(req.prompt, self.page_size)
                        hit_pages = self._prefix.usable_hit(
                            ns, chain, int(req.prompt.size))
                        # pin the hit BEFORE any eviction can run, or
                        # the eviction below could reclaim it
                        self._prefix.acquire(hit_pages)
                need = worst - len(hit_pages)
                if need > len(self._free_pages) \
                        and self._prefix is not None:
                    got = self._prefix.evict(
                        need - len(self._free_pages))
                    if got:
                        evicted += len(got)
                        self._free_pages = sorted(
                            self._free_pages + got)
                if need > len(self._free_pages):
                    if hit_pages:
                        self._prefix.release(hit_pages)
                    break  # page-pool backpressure
                self._queue.popleft()
                pages = list(hit_pages) + [
                    self._free_pages.pop(0) for _ in range(need)]
                seq = Sequence(req, slot, pages)
                if hit_pages:
                    seq.shared_count = len(hit_pages)
                    seq.done_prompt = len(hit_pages) * self.page_size
                    hits += 1
                elif ns is not None:
                    misses += 1
                seq.prefix_ns = ns
                seq.prefix_chain = chain
                self._slots[slot] = seq
                admitted.append(seq)
        if admitted:
            _flight.record(
                "serve", what="admit", n=len(admitted),
                queue=self.queue_depth(),
            )
            for seq in admitted:
                _reqtrace.on_admit(seq)
                if seq.shared_count:
                    _reqtrace.on_prefix_hit(
                        seq, seq.shared_count * self.page_size)
            if _metrics.enabled():
                _metrics.counter(
                    "serving_sequences_admitted",
                    help="sequences that joined the continuous batch",
                ).inc(len(admitted))
        if _metrics.enabled():
            if hits:
                _metrics.counter(
                    "serving_prefix_hits",
                    help="admissions that aliased cached prefix pages",
                ).inc(hits)
            if misses:
                _metrics.counter(
                    "serving_prefix_misses",
                    help="cache-eligible admissions with no usable "
                         "prefix hit",
                ).inc(misses)
            if evicted:
                _metrics.counter(
                    "serving_prefix_evictions",
                    help="refcount-0 cached pages reclaimed (LRU on "
                         "admission pressure, or the cache_evict_at_pass "
                         "chaos charge)",
                ).inc(evicted)
        self._record_gauges()
        return admitted

    def finish(self, seq: Sequence, *, error: Optional[str] = None) -> None:
        """Retire a sequence at an iteration boundary: result (or error)
        onto the request, slot and pages freed immediately.

        With the prefix cache on, an error-free sequence donates its
        FULL prompt pages (index < ``prompt_len // page_size`` — the
        only pages holding pure prompt KV: the last partial page carries
        the pad tail and decode writes) to the index instead of the free
        list; aliased pages are decref'd, dropping to the LRU when no
        other live sequence shares them."""
        req = seq.req
        req.generated = list(seq.generated)
        req.tokens = np.concatenate(
            [req.prompt, np.asarray(seq.generated, np.int32)])
        req.error = error
        req.finished_at = time.monotonic()
        with self._lock:
            self._slots[seq.slot] = None
            shared = seq.pages[:seq.shared_count]
            free = []
            cacheable = (
                self._prefix is not None and seq.prefix_ns is not None
                and seq.prefix_chain is not None and error is None
                and seq.done_prompt >= seq.prefill_len)
            if cacheable:
                nfull = min(seq.prompt_len // self.page_size,
                            len(seq.prefix_chain))
                for i in range(seq.shared_count, nfull):
                    if not self._prefix.insert(
                            seq.prefix_ns, seq.prefix_chain[i],
                            seq.pages[i]):
                        free.append(seq.pages[i])  # duplicate content
                free.extend(seq.pages[max(seq.shared_count, nfull):])
            else:
                free.extend(seq.pages[seq.shared_count:])
            if shared and self._prefix is not None:
                self._prefix.release(shared)
            # keep the free list sorted so page assignment is a pure
            # function of the admission order (deterministic replays)
            self._free_pages = sorted(self._free_pages + free)
        req._done.set()
        if _metrics.enabled():
            _metrics.counter(
                "serving_requests",
                help="generation requests completed, by rollout arm and "
                     "outcome",
                arm=req.arm,
                outcome="cancelled" if error
                and error.startswith("cancelled")
                else ("error" if error else "ok"),
            ).inc()
        # the one completion observation path: reqtrace closes the
        # request's span lifecycle, lands the e2e/TTFT/TPOT histograms
        # (including the old serving_request_latency_seconds alias), and
        # appends to the per-arm window the rollout/SLO gates read
        _reqtrace.on_finish(seq, error=error)
        self._record_gauges()

    # -------------------------------------------------------------- views

    def active(self, arm: Optional[str] = None) -> List[Sequence]:
        with self._lock:
            seqs = [s for s in self._slots if s is not None]
        if arm is not None:
            seqs = [s for s in seqs if s.arm == arm]
        return seqs

    def arms_active(self) -> List[str]:
        seen: Dict[str, bool] = {}
        for s in self.active():
            seen.setdefault(s.arm, True)
        return list(seen)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def relabel_arm(self, src: str, dst: str) -> None:
        """Move every queued request and in-flight sequence from arm `src`
        to `dst`. Legal ONLY when `dst` holds the same params as `src`
        (promotion: identical weights under a new label) — a sequence must
        never change weights mid-decode."""
        moved: List[Request] = []
        with self._lock:
            for req in self._queue:
                if req.arm == src:
                    req.arm = dst
                    moved.append(req)
            for s in self._slots:
                if s is not None and s.arm == src:
                    s.arm = dst
                    s.req.arm = dst
                    moved.append(s.req)
        for req in moved:
            _reqtrace.on_relabel(req, src, dst)

    def relabel_queued_only(self, src: str, dst: str) -> None:
        """Re-route queued `src` requests to `dst` without touching
        in-flight sequences (the rollback path: admitted canary work
        drains on its own weights)."""
        moved: List[Request] = []
        with self._lock:
            for req in self._queue:
                if req.arm == src:
                    req.arm = dst
                    moved.append(req)
        for req in moved:
            _reqtrace.on_relabel(req, src, dst)

    def move_active_to_drain(self, src: str, drain_label: str) -> int:
        """Re-bind in-flight `src` sequences to `drain_label` — the SAME
        params parked under a private label so they finish coherently
        while `src` is handed to a new weight generation. ``req.arm`` (the
        metrics/routing label) is untouched. Returns how many moved."""
        n = 0
        with self._lock:
            for s in self._slots:
                if s is not None and s.arm == src:
                    s.arm = drain_label
                    n += 1
        return n

    def pages_in_use(self) -> int:
        """Distinct pages held by *active* sequences (aliased pages
        count once — that is the sharing win). Pages resident only in
        the prefix cache are neither in use nor free; see
        :meth:`cached_page_count`."""
        with self._lock:
            return len({p for s in self._slots if s is not None
                        for p in s.pages})

    def free_page_count(self) -> int:
        with self._lock:
            return len(self._free_pages)

    def cached_page_count(self) -> int:
        """Pages held by the prefix-cache index (shared + idle)."""
        with self._lock:
            return 0 if self._prefix is None \
                else self._prefix.resident_pages()

    def prefix_summary(self, limit: int = 64) -> List[str]:
        """Content block digests of the resident prefix cache — the
        locality signal a fleet replica advertises in its status blob."""
        with self._lock:
            return [] if self._prefix is None \
                else self._prefix.block_summary(limit)

    def chaos_evict(self) -> Tuple[int, int]:
        """``HOROVOD_CHAOS=cache_evict_at_pass=K``'s forced mid-flight
        eviction: drop EVERY refcount-0 cached page, then tear shared
        pages out from under live sequences — each victim swaps its
        aliased pages for fresh owned ones and restarts prefill from
        position 0, rewriting the same KV bit-identically (the drill's
        whole point: tokens must not change). Returns
        ``(victims, pages_dropped)``. Must only run at an iteration
        boundary — mid-pass it would invalidate captured batch rows."""
        victims = 0
        dropped = 0
        with self._lock:
            if self._prefix is None:
                return (0, 0)
            got = self._prefix.evict(self._prefix.evictable())
            dropped += len(got)
            self._free_pages = sorted(self._free_pages + got)
            for s in self._slots:
                if s is None or not s.shared_count:
                    continue
                if len(self._free_pages) < s.shared_count:
                    continue  # no replacement pages: leave it aliased
                shared = s.pages[:s.shared_count]
                repl = [self._free_pages.pop(0)
                        for _ in range(s.shared_count)]
                s.pages = repl + s.pages[s.shared_count:]
                s.shared_count = 0
                s.restart_prefill()
                self._prefix.release(shared)
                victims += 1
            # pages the victims released may have hit refcount 0 — the
            # drill drops those too
            got = self._prefix.evict(self._prefix.evictable())
            dropped += len(got)
            self._free_pages = sorted(self._free_pages + got)
        if dropped and _metrics.enabled():
            _metrics.counter(
                "serving_prefix_evictions",
                help="refcount-0 cached pages reclaimed (LRU on "
                     "admission pressure, or the cache_evict_at_pass "
                     "chaos charge)",
            ).inc(dropped)
        return (victims, dropped)

    def idle(self) -> bool:
        with self._lock:
            return not self._queue and all(
                s is None for s in self._slots)

    def page_table_rows(self) -> np.ndarray:
        """``[max_batch, pages_per_seq]`` int32 page table for the current
        batch composition: admitted rows get their pages (tail-padded with
        the trash page), empty slots are all-trash."""
        table = np.zeros((self.max_batch, self.pages_per_seq), np.int32)
        with self._lock:
            for i, s in enumerate(self._slots):
                if s is not None:
                    table[i, :len(s.pages)] = np.asarray(s.pages, np.int32)
        return table

    def _record_gauges(self) -> None:
        if not _metrics.enabled():
            return
        _metrics.gauge(
            "serving_queue_depth",
            help="requests queued awaiting a slot + page reservation",
        ).set(self.queue_depth())
        _metrics.gauge(
            "serving_active_sequences",
            help="sequences currently holding a batch slot",
        ).set(len(self.active()))
        _metrics.gauge(
            "serving_pages_in_use",
            help="kv-cache pages currently reserved by admitted sequences",
        ).set(self.pages_in_use())
        _metrics.gauge(
            "serving_page_pool_pages",
            help="allocatable kv-cache pages in the pool (excludes the "
                 "trash page)",
        ).set(self.num_pages - 1)
        if self._prefix is not None:
            with self._lock:
                shared = self._prefix.shared_page_count()
                resident = self._prefix.resident_pages()
            _metrics.gauge(
                "serving_prefix_pages_shared",
                help="cached pages aliased by at least one live "
                     "sequence",
            ).set(shared)
            _metrics.gauge(
                "serving_prefix_pages_resident",
                help="pool pages held by the prefix-cache index "
                     "(shared + idle)",
            ).set(resident)
