"""Canary / promotion / rollback of weight generations through the engine.

The control-plane coupling this repo uniquely has (ROADMAP item 4): a new
weight generation is never flipped onto the whole fleet blind —

1. **Numerics gate, by construction.** The trainer's publish gate
   (:class:`~horovod_tpu.serving.publisher.PublishRejected`, PR 9) sits
   *before* any byte reaches the KV, so a generation whose gradients were
   non-finite, mid-bad-streak, or quarantine-tainted **never arrives** at
   this controller — the first line of defense costs serving nothing.
2. **Canary slice.** A generation that does arrive serves a deterministic
   slice of traffic (``canary_fraction``, hashed on the request id — the
   same request always lands in the same arm) on the engine's ``canary``
   arm while the ``stable`` arm keeps serving generation G−1.
3. **Serving-metrics gate.** After ``min_canary_requests`` completed
   canary requests, the live per-arm metrics decide: an error-rate excess
   (non-finite logits are an engine-detected error — the signature of
   weights a gate-less trainer would have shipped) or a latency blow-up
   versus stable **auto-rolls back** to G−1; otherwise the canary
   **promotes**. Both verdicts ride the ordinary metric families
   (``serving_requests{arm=,outcome=}``,
   ``serving_request_latency_seconds{arm=}``), so the ``/fleet``
   aggregation plane shows per-generation deltas fleet-wide.

A rolled-back generation is **vetoed**: the subscriber may hold it (its
chain marched on), but the engine never serves it again — the next
generation starts a fresh canary on top of the same stable weights.
In-flight canary sequences are never dropped on rollback; the canary arm
drains and only then releases its params.
"""

from __future__ import annotations

import logging
import os
import zlib
from typing import Callable, Dict, List, Optional

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.serving.engine import note_subscriber_health
from horovod_tpu.serving.scheduler import Request

__all__ = [
    "GenerationRollout",
    "CANARY_FRACTION_ENV",
    "CANARY_MIN_REQUESTS_ENV",
]

logger = logging.getLogger("horovod_tpu.serving")

CANARY_FRACTION_ENV = "HOROVOD_SERVING_CANARY_FRACTION"
CANARY_MIN_REQUESTS_ENV = "HOROVOD_SERVING_CANARY_MIN_REQUESTS"

#: serving_rollout_state encoding
STATE_STABLE = 0
STATE_CANARY = 1


class GenerationRollout:
    """Drive an :class:`~horovod_tpu.serving.engine.InferenceEngine`'s
    weight arms from a subscriber, canarying every new generation.

    - :meth:`poll` — pull the subscriber, start/refresh the canary.
    - :meth:`submit` — route a request to its arm and track it.
    - :meth:`pump` — one engine iteration + harvest finished requests +
      evaluate the promotion/rollback gate (call in the serving loop).

    `max_error_rate` is the canary error-rate ceiling (default 0.0 — any
    engine-detected error on the canary slice rolls back; stable-arm
    errors never indict the canary). `max_latency_ratio` (default 3.0)
    bounds canary/stable mean request latency once both arms have a
    window. `on_event(event, generation)` observes ``canary_started`` /
    ``promoted`` / ``rolled_back``.
    """

    def __init__(self, engine, subscriber, *,
                 canary_fraction: Optional[float] = None,
                 min_canary_requests: Optional[int] = None,
                 max_error_rate: float = 0.0,
                 max_latency_ratio: Optional[float] = 3.0,
                 on_event: Optional[Callable[[str, int], None]] = None):
        self._engine = engine
        self._sub = subscriber
        self.canary_fraction = float(
            canary_fraction if canary_fraction is not None
            else os.environ.get(CANARY_FRACTION_ENV, "0.25"))
        self.min_canary_requests = int(
            min_canary_requests if min_canary_requests is not None
            else os.environ.get(CANARY_MIN_REQUESTS_ENV, "8"))
        self.max_error_rate = float(max_error_rate)
        self.max_latency_ratio = max_latency_ratio
        self._on_event = on_event
        self._stable_gen: Optional[int] = None
        self._canary_gen: Optional[int] = None
        self._vetoed: set = set()
        self._outstanding: List[Request] = []
        # per-arm completion window, reset when a canary starts
        self._window: Dict[str, Dict[str, float]] = {}
        self._reset_window()
        self._record_state()

    # ------------------------------------------------------------- weights

    @property
    def stable_generation(self) -> Optional[int]:
        return self._stable_gen

    @property
    def canary_generation(self) -> Optional[int]:
        return self._canary_gen

    @property
    def vetoed(self) -> frozenset:
        return frozenset(self._vetoed)

    def poll(self) -> None:
        """Advance the subscriber; a new generation either bootstraps the
        stable arm (first weights) or starts/refreshes the canary. Also
        feeds the staleness health bridge every call."""
        self._sub.poll()
        note_subscriber_health(self._sub)
        gen = self._sub.generation
        tree = self._sub.weights()
        if tree is None or gen in self._vetoed:
            return
        if self._stable_gen is None:
            self._stable_gen = gen
            self._engine.set_weights(tree, generation=gen, arm="stable")
            logger.info("rollout: stable bootstrap at generation %d", gen)
            self._record_state()
            return
        if gen == self._stable_gen or gen == self._canary_gen:
            return
        # a NEWER generation while one is already canarying restarts the
        # evaluation window on the newest candidate — promoting a
        # half-evaluated middle generation would skip its own gate
        self._canary_gen = gen
        self._engine.set_weights(tree, generation=gen, arm="canary")
        # canary requests still QUEUED will decode against the NEW
        # weights (only in-flight sequences park on the old generation's
        # drain arm), so their verdicts belong to THIS evaluation window
        active_now = {
            id(s.req) for s in self._engine.scheduler.active()
        }
        for req in self._outstanding:
            if (req.arm == "canary" and not req.done
                    and id(req) not in active_now):
                req.rollout_gen = gen
        self._reset_window()
        logger.info(
            "rollout: canarying generation %d on %.0f%% of traffic "
            "(stable %d)", gen, 100 * self.canary_fraction,
            self._stable_gen)
        self._emit("canary_started", gen)
        self._record_state()

    # ------------------------------------------------------------ requests

    def route(self, rid) -> str:
        """Deterministic traffic split: the same request id always lands
        in the same arm (crc32 hash — no RNG, replayable)."""
        if self._canary_gen is None:
            return "stable"
        h = zlib.crc32(str(rid).encode()) % 10000
        return "canary" if h < int(self.canary_fraction * 10000) else "stable"

    def submit(self, rid, prompt, max_new_tokens: int,
               temperature: float = 0.0) -> Request:
        req = Request(rid, prompt, max_new_tokens,
                      temperature=temperature, arm=self.route(rid))
        # which canary evaluation this request belongs to: a request from
        # a rolled-back (or superseded) canary must never be harvested
        # into a LATER generation's gate window
        req.rollout_gen = (self._canary_gen if req.arm == "canary"
                           else self._stable_gen)
        self._engine.submit(req)
        self._outstanding.append(req)
        return req

    # ----------------------------------------------------------- the loop

    def pump(self) -> bool:
        """One serving-loop turn: engine iteration, harvest completions
        into the per-arm window, evaluate the gate. Returns the engine's
        progress flag."""
        ran = self._engine.step()
        still: List[Request] = []
        for req in self._outstanding:
            if not req.done:
                still.append(req)
                continue
            if (req.arm == "canary"
                    and getattr(req, "rollout_gen", None)
                    != self._canary_gen):
                # a leftover from a rolled-back / superseded canary: its
                # verdict belongs to THAT generation, not the one under
                # evaluation now
                continue
            w = self._window[req.arm]
            w["done"] += 1
            if req.error:
                w["errors"] += 1
            lat = req.latency_seconds()
            if lat is not None:
                w["latency_sum"] += lat
        self._outstanding = still
        self._evaluate()
        return ran

    def drain(self, max_iters: int = 10000) -> None:
        """Pump until every outstanding request completed."""
        for _ in range(max_iters):
            if not self._outstanding:
                return
            self.pump()
        raise RuntimeError(
            f"rollout did not drain within {max_iters} iterations")

    # ---------------------------------------------------------- the gates

    def _evaluate(self) -> None:
        if self._canary_gen is None:
            return
        c = self._window["canary"]
        if c["done"] < self.min_canary_requests:
            return
        err_rate = c["errors"] / c["done"]
        if err_rate > self.max_error_rate:
            self._rollback(
                f"error rate {err_rate:.2f} > {self.max_error_rate:.2f} "
                f"over {int(c['done'])} canary requests")
            return
        s = self._window["stable"]
        if (self.max_latency_ratio is not None and s["done"] > 0
                and s["latency_sum"] > 0):
            ratio = (c["latency_sum"] / c["done"]) / (
                s["latency_sum"] / s["done"])
            if ratio > self.max_latency_ratio:
                self._rollback(
                    f"latency ratio {ratio:.2f}x > "
                    f"{self.max_latency_ratio:.2f}x vs stable")
                return
        self._promote()

    def _promote(self) -> None:
        gen = self._canary_gen
        self._engine.promote_canary()
        self._stable_gen = gen
        self._canary_gen = None
        self._reset_window()
        logger.info("rollout: promoted generation %d to stable", gen)
        if _metrics.enabled():
            _metrics.counter(
                "serving_rollouts",
                help="canary evaluations concluded, by outcome",
                outcome="promoted",
            ).inc()
        self._emit("promoted", gen)
        self._record_state()

    def _rollback(self, why: str) -> None:
        gen = self._canary_gen
        self._vetoed.add(gen)
        self._engine.retire_arm("canary")
        self._canary_gen = None
        self._reset_window()
        logger.warning(
            "rollout: generation %d rolled back to %d (%s)",
            gen, self._stable_gen, why)
        if _metrics.enabled():
            _metrics.counter(
                "serving_rollouts",
                help="canary evaluations concluded, by outcome",
                outcome="rolled_back",
            ).inc()
        self._emit("rolled_back", gen)
        self._record_state()

    # ------------------------------------------------------------ plumbing

    def _reset_window(self) -> None:
        self._window = {
            arm: {"done": 0.0, "errors": 0.0, "latency_sum": 0.0}
            for arm in ("stable", "canary")
        }

    def _emit(self, event: str, generation: int) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(event, generation)
        except Exception as e:
            logger.debug("rollout on_event callback failed: %s", e)

    def _record_state(self) -> None:
        if not _metrics.enabled():
            return
        _metrics.gauge(
            "serving_rollout_state",
            help="0 = serving stable only, 1 = canary in flight",
        ).set(STATE_CANARY if self._canary_gen is not None
              else STATE_STABLE)
        if self._stable_gen is not None:
            _metrics.gauge(
                "serving_stable_generation",
                help="generation the stable arm serves",
            ).set(self._stable_gen)
        _metrics.gauge(
            "serving_canary_generation",
            help="generation under canary (-1 = none)",
        ).set(-1 if self._canary_gen is None else self._canary_gen)
