"""Canary / promotion / rollback of weight generations through the engine.

The control-plane coupling this repo uniquely has (ROADMAP item 4): a new
weight generation is never flipped onto the whole fleet blind —

1. **Numerics gate, by construction.** The trainer's publish gate
   (:class:`~horovod_tpu.serving.publisher.PublishRejected`, PR 9) sits
   *before* any byte reaches the KV, so a generation whose gradients were
   non-finite, mid-bad-streak, or quarantine-tainted **never arrives** at
   this controller — the first line of defense costs serving nothing.
2. **Canary slice.** A generation that does arrive serves a deterministic
   slice of traffic (``canary_fraction``, hashed on the request id — the
   same request always lands in the same arm) on the engine's ``canary``
   arm while the ``stable`` arm keeps serving generation G−1.
3. **Serving-metrics gate.** After ``min_canary_requests`` completed
   canary requests, the live per-arm completion windows
   (:mod:`horovod_tpu.observability.reqtrace` — the ONE observation
   path, shared with the ``reqtrace_*``/``serving_request_latency``
   histograms) decide: an error-rate excess (non-finite logits are an
   engine-detected error — the signature of weights a gate-less trainer
   would have shipped), a latency blow-up versus stable, or any
   declared SLO objective burning on the canary slice
   (:meth:`horovod_tpu.observability.slo.SLORegistry.judge_canary`,
   judged against the stable arm's live baseline, with the objective
   named to the health machine) **auto-rolls back** to G−1; otherwise
   the canary **promotes**. The same completions feed the ``/fleet``
   aggregation plane, so per-generation deltas are visible fleet-wide.

A rolled-back generation is **vetoed**: the subscriber may hold it (its
chain marched on), but the engine never serves it again — the next
generation starts a fresh canary on top of the same stable weights.
In-flight canary sequences are never dropped on rollback; the canary arm
drains and only then releases its params.
"""

from __future__ import annotations

import logging
import os
import zlib
from typing import Callable, Dict, List, Optional

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import reqtrace as _reqtrace
from horovod_tpu.observability import slo as _slo
from horovod_tpu.resilience import health as _health
from horovod_tpu.serving.engine import note_subscriber_health
from horovod_tpu.serving.scheduler import Request

__all__ = [
    "GenerationRollout",
    "judge_window",
    "CANARY_FRACTION_ENV",
    "CANARY_MIN_REQUESTS_ENV",
]

logger = logging.getLogger("horovod_tpu.serving")

CANARY_FRACTION_ENV = "HOROVOD_SERVING_CANARY_FRACTION"
CANARY_MIN_REQUESTS_ENV = "HOROVOD_SERVING_CANARY_MIN_REQUESTS"

#: serving_rollout_state encoding
STATE_STABLE = 0
STATE_CANARY = 1


def judge_window(canary: Dict[str, object], stable: Dict[str, object], *,
                 min_requests: int, max_error_rate: float = 0.0,
                 max_latency_ratio: Optional[float] = 3.0, slo=None):
    """The canary gate as a pure function over completion windows (the
    dict shape :func:`horovod_tpu.observability.reqtrace.arm_window`
    returns), so one engine's rollout and the fleet tier's merged
    multi-replica windows judge through the SAME logic. Returns None
    while `canary` has fewer than `min_requests` completions, else
    ``("promote", "", None)`` or ``("rollback", why, objective)`` where
    `objective` names the burning SLO when that gate tripped (callers
    feed it to the health machine)."""
    done = int(canary["done"])  # type: ignore[arg-type]
    if done < min_requests:
        return None
    err_rate = int(canary["errors"]) / done  # type: ignore[arg-type]
    if err_rate > max_error_rate:
        return ("rollback",
                f"error rate {err_rate:.2f} > {max_error_rate:.2f} "
                f"over {done} canary requests", None)
    if (max_latency_ratio is not None and stable["done"] > 0
            and stable["latency_sum"] > 0):
        ratio = (canary["latency_sum"] / done) / (  # type: ignore
            stable["latency_sum"] / stable["done"])  # type: ignore
        if ratio > max_latency_ratio:
            return ("rollback",
                    f"latency ratio {ratio:.2f}x > "
                    f"{max_latency_ratio:.2f}x vs stable", None)
    registry = slo if slo is not None else _slo.default()
    verdict = registry.judge_canary(canary, stable)
    if verdict is not None:
        name, detail = verdict
        return ("rollback",
                f"slo objective '{name}' burning on canary: {detail}",
                name)
    return ("promote", "", None)


class GenerationRollout:
    """Drive an :class:`~horovod_tpu.serving.engine.InferenceEngine`'s
    weight arms from a subscriber, canarying every new generation.

    - :meth:`poll` — pull the subscriber, start/refresh the canary.
    - :meth:`submit` — route a request to its arm and track it.
    - :meth:`pump` — one engine iteration + harvest finished requests +
      evaluate the promotion/rollback gate (call in the serving loop).

    `max_error_rate` is the canary error-rate ceiling (default 0.0 — any
    engine-detected error on the canary slice rolls back; stable-arm
    errors never indict the canary). `max_latency_ratio` (default 3.0)
    bounds canary/stable mean request latency once both arms have a
    window. `slo` is the objective evaluator the canary gate judges
    through (default: the process-wide
    :func:`horovod_tpu.observability.slo.default` registry — any
    declared serving-side objective burning on the canary slice, judged
    against the stable arm's live baseline, rolls back with the
    objective named). `on_event(event, generation)` observes
    ``canary_started`` / ``promoted`` / ``rolled_back``.
    """

    def __init__(self, engine, subscriber, *,
                 canary_fraction: Optional[float] = None,
                 min_canary_requests: Optional[int] = None,
                 max_error_rate: float = 0.0,
                 max_latency_ratio: Optional[float] = 3.0,
                 slo=None,
                 on_event: Optional[Callable[[str, int], None]] = None):
        self._engine = engine
        self._sub = subscriber
        self.canary_fraction = float(
            canary_fraction if canary_fraction is not None
            else os.environ.get(CANARY_FRACTION_ENV, "0.25"))
        self.min_canary_requests = int(
            min_canary_requests if min_canary_requests is not None
            else os.environ.get(CANARY_MIN_REQUESTS_ENV, "8"))
        self.max_error_rate = float(max_error_rate)
        self.max_latency_ratio = max_latency_ratio
        self._slo = slo
        self._on_event = on_event
        self._stable_gen: Optional[int] = None
        self._canary_gen: Optional[int] = None
        self._vetoed: set = set()
        self._outstanding: List[Request] = []
        # per-arm completion-window marks into the reqtrace series,
        # re-taken when a canary starts (the gate reads "what completed
        # since")
        self._marks: Dict[str, int] = {}
        self._reset_window()
        self._record_state()

    # ------------------------------------------------------------- weights

    @property
    def stable_generation(self) -> Optional[int]:
        return self._stable_gen

    @property
    def canary_generation(self) -> Optional[int]:
        return self._canary_gen

    @property
    def vetoed(self) -> frozenset:
        return frozenset(self._vetoed)

    def poll(self) -> None:
        """Advance the subscriber; a new generation either bootstraps the
        stable arm (first weights) or starts/refreshes the canary. Also
        feeds the staleness health bridge every call."""
        self._sub.poll()
        note_subscriber_health(self._sub)
        gen = self._sub.generation
        tree = self._sub.weights()
        if tree is None or gen in self._vetoed:
            return
        if self._stable_gen is None:
            self._stable_gen = gen
            self._engine.set_weights(tree, generation=gen, arm="stable")
            logger.info("rollout: stable bootstrap at generation %d", gen)
            self._record_state()
            return
        if gen == self._stable_gen or gen == self._canary_gen:
            return
        # a NEWER generation while one is already canarying restarts the
        # evaluation window on the newest candidate — promoting a
        # half-evaluated middle generation would skip its own gate
        self._canary_gen = gen
        self._engine.set_weights(tree, generation=gen, arm="canary")
        # canary requests still QUEUED will decode against the NEW
        # weights (only in-flight sequences park on the old generation's
        # drain arm) — reqtrace tags every completion with the weight
        # generation that actually decoded it, so the gate's
        # generation-filtered window sorts this out by construction
        self._reset_window()
        logger.info(
            "rollout: canarying generation %d on %.0f%% of traffic "
            "(stable %d)", gen, 100 * self.canary_fraction,
            self._stable_gen)
        self._emit("canary_started", gen)
        self._record_state()

    # ------------------------------------------------------------ requests

    def route(self, rid) -> str:
        """Deterministic traffic split: the same request id always lands
        in the same arm (crc32 hash — no RNG, replayable)."""
        if self._canary_gen is None:
            return "stable"
        h = zlib.crc32(str(rid).encode()) % 10000
        return "canary" if h < int(self.canary_fraction * 10000) else "stable"

    def submit(self, rid, prompt, max_new_tokens: int,
               temperature: float = 0.0) -> Request:
        req = Request(rid, prompt, max_new_tokens,
                      temperature=temperature, arm=self.route(rid))
        self._engine.submit(req)
        self._outstanding.append(req)
        return req

    # ----------------------------------------------------------- the loop

    def pump(self) -> bool:
        """One serving-loop turn: engine iteration + evaluate the gate
        (completions accumulate in the reqtrace per-arm windows as the
        scheduler retires them — no separate harvest). Returns the
        engine's progress flag."""
        ran = self._engine.step()
        self._outstanding = [r for r in self._outstanding if not r.done]
        self._evaluate()
        return ran

    def drain(self, max_iters: int = 10000) -> None:
        """Pump until every outstanding request completed."""
        for _ in range(max_iters):
            if not self._outstanding:
                return
            self.pump()
        raise RuntimeError(
            f"rollout did not drain within {max_iters} iterations")

    # ---------------------------------------------------------- the gates

    def _evaluate(self) -> None:
        if self._canary_gen is None:
            return
        # the canary window is generation-filtered: a leftover from a
        # rolled-back / superseded canary completed under THAT
        # generation's weights and never pollutes this gate
        c = _reqtrace.arm_window(
            "canary", since=self._marks.get("canary", 0),
            generation=self._canary_gen)
        s = _reqtrace.arm_window(
            "stable", since=self._marks.get("stable", 0))
        verdict = judge_window(
            c, s, min_requests=self.min_canary_requests,
            max_error_rate=self.max_error_rate,
            max_latency_ratio=self.max_latency_ratio, slo=self._slo)
        if verdict is None:
            return
        action, why, objective = verdict
        if action == "promote":
            self._promote()
            return
        if objective is not None:
            _health.record_slo_burn(
                objective, f"canary generation {self._canary_gen}")
        self._rollback(why)

    def _promote(self) -> None:
        gen = self._canary_gen
        self._engine.promote_canary()
        self._stable_gen = gen
        self._canary_gen = None
        self._reset_window()
        logger.info("rollout: promoted generation %d to stable", gen)
        if _metrics.enabled():
            _metrics.counter(
                "serving_rollouts",
                help="canary evaluations concluded, by outcome",
                outcome="promoted",
            ).inc()
        self._emit("promoted", gen)
        self._record_state()

    def _rollback(self, why: str) -> None:
        gen = self._canary_gen
        self._vetoed.add(gen)
        self._engine.retire_arm("canary")
        self._canary_gen = None
        self._reset_window()
        logger.warning(
            "rollout: generation %d rolled back to %d (%s)",
            gen, self._stable_gen, why)
        if _metrics.enabled():
            _metrics.counter(
                "serving_rollouts",
                help="canary evaluations concluded, by outcome",
                outcome="rolled_back",
            ).inc()
        self._emit("rolled_back", gen)
        self._record_state()

    # ------------------------------------------------------------ plumbing

    def _reset_window(self) -> None:
        self._marks = {
            arm: _reqtrace.arm_mark(arm) for arm in ("stable", "canary")
        }

    def _emit(self, event: str, generation: int) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(event, generation)
        except Exception as e:
            logger.debug("rollout on_event callback failed: %s", e)

    def _record_state(self) -> None:
        if not _metrics.enabled():
            return
        _metrics.gauge(
            "serving_rollout_state",
            help="0 = serving stable only, 1 = canary in flight",
        ).set(STATE_CANARY if self._canary_gen is not None
              else STATE_STABLE)
        if self._stable_gen is not None:
            _metrics.gauge(
                "serving_stable_generation",
                help="generation the stable arm serves",
            ).set(self._stable_gen)
        _metrics.gauge(
            "serving_canary_generation",
            help="generation under canary (-1 = none)",
        ).set(-1 if self._canary_gen is None else self._canary_gen)
