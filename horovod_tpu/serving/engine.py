"""Continuous-batching transformer inference engine on subscribed weights.

The serving half of ROADMAP item 4's "millions of users" story: weights
stream in through :class:`~horovod_tpu.serving.subscriber.WeightSubscriber`
(train → publish → **serve**), and this engine turns them into tokens under
real request traffic:

- **Paged KV cache** — every layer's cache is one preallocated pool of
  fixed-size pages (``[num_pages, page_size, H_kv, D]``); sequences own
  pages through per-slot page tables, so ONE compiled decode step serves
  any batch composition with fully static shapes (the vLLM memory model).
  The decode-attention path is
  :func:`horovod_tpu.ops.flash_attention.paged_decode_attention` — the
  same primitive :func:`horovod_tpu.models.transformer.generate` uses,
  reached through a page-table gather.
- **Continuous batching** — requests join the batched decode loop at any
  iteration boundary and finished sequences free their slot + pages at
  the boundary they finish (Orca's iteration-level scheduling). Prefill
  is **chunked** (``prefill_chunk`` tokens per iteration) into the same
  schedule, so a long prompt shares iterations with in-flight decodes
  instead of stalling them.
- **Weight arms** — the engine holds one parameter tree per rollout arm
  (``stable``, and ``canary`` while a
  :class:`~horovod_tpu.serving.rollout.GenerationRollout` is evaluating a
  new generation). Params are a *runtime argument* of the one compiled
  step, so arms share the compilation and the page pool.

The engine adds **no training-side collectives**: every jitted function
here is per-process dense compute (pinned by
``tests/test_serving_engine.py`` extracting its collective schedule), so
serving can share a host with training without perturbing the PR-8
schedule fingerprints.

Degrade-don't-crash composes end to end: a stalled subscriber keeps the
engine serving generation ``G−k`` while
:func:`note_subscriber_health` flips ``/health`` to 503 with the lag in
the reason; in-flight sequences are never dropped.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import reqtrace as _reqtrace
from horovod_tpu.resilience import chaos as _chaos
from horovod_tpu.serving.scheduler import (
    ContinuousBatchingScheduler,
    QueueFull,
    Request,
)

__all__ = [
    "InferenceEngine",
    "note_subscriber_health",
    "PAGE_SIZE_ENV",
    "PAGES_ENV",
    "MAX_BATCH_ENV",
    "PREFILL_CHUNK_ENV",
    "MAX_QUEUE_ENV",
    "PREFIX_CACHE_ENV",
    "SPEC_LOOKAHEAD_ENV",
    "SPEC_DRAFT_DEPTH_ENV",
    "TP_AXIS_ENV",
]

logger = logging.getLogger("horovod_tpu.serving")

PAGE_SIZE_ENV = "HOROVOD_ENGINE_PAGE_SIZE"
PAGES_ENV = "HOROVOD_ENGINE_PAGES"
MAX_BATCH_ENV = "HOROVOD_ENGINE_MAX_BATCH"
PREFILL_CHUNK_ENV = "HOROVOD_ENGINE_PREFILL_CHUNK"
MAX_QUEUE_ENV = "HOROVOD_ENGINE_MAX_QUEUE"
#: "1" (default) aliases cached prompt pages at admission; "0" disables
PREFIX_CACHE_ENV = "HOROVOD_PREFIX_CACHE"
#: draft tokens proposed per speculative iteration (>= 1)
SPEC_LOOKAHEAD_ENV = "HOROVOD_SPEC_LOOKAHEAD"
#: transformer blocks in the derived draft model; 0 (default) = no
#: draft, speculative decoding off
SPEC_DRAFT_DEPTH_ENV = "HOROVOD_SPEC_DRAFT_DEPTH"
#: mesh axis name to tensor-parallel the serving path over (unset = off)
TP_AXIS_ENV = "HOROVOD_TP_AXIS"


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def note_subscriber_health(sub) -> None:
    """Publish the serving-side staleness view and feed the health plane:
    ``serving_subscriber_lag`` / ``serving_staleness_seconds`` gauges
    (which ride :class:`~horovod_tpu.observability.aggregate
    .MetricsPublisher` to ``/fleet`` and ``hvd_top`` like every other
    metric), and a ``stale()`` subscriber flips the existing ``/health``
    endpoint to 503 with the lag in the reason
    (:func:`horovod_tpu.resilience.health.record_serving_stale`) until
    the weights are fresh again."""
    from horovod_tpu.resilience import health as _health

    lag = sub.lag()
    age = sub.staleness_seconds()
    if _metrics.enabled():
        _metrics.gauge(
            "serving_subscriber_lag",
            help="generations between the observed head and what the "
                 "engine serves",
        ).set(lag)
        if age is not None:
            _metrics.gauge(
                "serving_staleness_seconds",
                help="wall-clock age of the weights the engine serves",
            ).set(age)
    if sub.stale():
        _health.record_serving_stale(lag, age)
    else:
        _health.record_serving_fresh()


class _Arm:
    def __init__(self, generation: int, params: Any):
        self.generation = generation
        self.params = params
        self.draining = False


class InferenceEngine:
    """Serve a :class:`~horovod_tpu.models.transformer.TransformerLM`
    under continuous batching on a paged KV cache.

    `model` is the *training-shape* module (``decode=False``); the engine
    derives its paged decode twin. Weights arrive via
    :meth:`set_weights` (or :meth:`poll_weights` from an attached
    subscriber); requests via :meth:`submit`; :meth:`step` runs one
    iteration boundary (admission → chunked prefill → batched decode) and
    :meth:`run_until_idle` drains everything queued.

    Greedy decoding through this engine is token-identical to
    :func:`horovod_tpu.models.transformer.generate` for any ragged batch
    and any join/leave order — pinned by
    ``tests/test_serving_engine.py``.
    """

    def __init__(self, model, *, page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 subscriber=None, eos_token: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 draft_model=None,
                 draft_depth: Optional[int] = None,
                 spec_lookahead: Optional[int] = None,
                 tp_axis: Optional[str] = None):
        import jax

        self._model = model
        self.page_size = int(page_size if page_size is not None
                             else _env_int(PAGE_SIZE_ENV, 16))
        self.num_pages = int(num_pages if num_pages is not None
                             else _env_int(PAGES_ENV, 64))
        self.max_batch = int(max_batch if max_batch is not None
                             else _env_int(MAX_BATCH_ENV, 4))
        self.prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else _env_int(PREFILL_CHUNK_ENV, 16))
        max_queue = int(max_queue if max_queue is not None
                        else _env_int(MAX_QUEUE_ENV, 64))
        self.max_seq_len = int(max_seq_len if max_seq_len is not None
                               else model.max_len)
        if self.max_seq_len > model.max_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"max_len {model.max_len}")
        # per-slot page budget, with the capacity rounded up to a whole
        # number of prefill chunks: prefill chunk starts are multiples of
        # prefill_chunk, so a chunk's masked pad tail can never be clamped
        # back INTO the slot's real pages (it either lands at positions the
        # next real write overwrites, or past the row's final frontier
        # where the causal mask hides it)
        pages = -(-self.max_seq_len // self.page_size)
        while (pages * self.page_size) % self.prefill_chunk:
            pages += 1
        self.pages_per_seq = pages
        if self.pages_per_seq > self.num_pages - 1:
            raise ValueError(
                f"page pool too small: one sequence can need "
                f"{self.pages_per_seq} pages, pool has "
                f"{self.num_pages - 1} allocatable (raise {PAGES_ENV} or "
                f"lower max_seq_len)")
        # tensor-parallel serving: param trees land head/feature-sharded
        # over `tp_axis` (transformer_param_specs layouts) and the page
        # pool is head-sharded, so the SAME jitted step partitions over
        # the axis under GSPMD — token-identical to single-chip serving
        # because per-head attention needs no cross-rank reductions and
        # the two per-block psums are bit-deterministic on a fixed mesh
        self.tp_axis = (tp_axis if tp_axis is not None
                        else os.environ.get(TP_AXIS_ENV, "").strip() or None)
        self._mesh = None
        if self.tp_axis:
            from horovod_tpu import basics

            mesh = basics.mesh()
            if self.tp_axis not in mesh.shape:
                raise ValueError(
                    f"tp_axis {self.tp_axis!r} is not an axis of the "
                    f"active mesh (axes: {tuple(mesh.shape)})")
            tp = mesh.shape[self.tp_axis]
            h_kv = model.kv_heads or model.heads
            if model.heads % tp or h_kv % tp:
                raise ValueError(
                    f"heads={model.heads} / kv_heads={h_kv} not divisible "
                    f"by tp axis {self.tp_axis!r} size {tp}")
            self._mesh = mesh
        self.prefix_caching = bool(
            prefix_cache if prefix_cache is not None
            else _env_int(PREFIX_CACHE_ENV, 1))
        self._sched = ContinuousBatchingScheduler(
            num_pages=self.num_pages, page_size=self.page_size,
            max_batch=self.max_batch, pages_per_seq=self.pages_per_seq,
            max_queue=max_queue, prefill_chunk=self.prefill_chunk,
            prefix_cache=self.prefix_caching,
            namespace_of=self._arm_namespace)
        self._subscriber = subscriber
        self.eos_token = eos_token
        # fleet-tier identity: set by FleetReplica so chaos charges can
        # target one replica (``slow_decode=<s>:<arm>@<replica>``) and
        # reqtrace can attribute spans to the engine that served them
        self.replica: Optional[str] = None
        self._arms: Dict[str, _Arm] = {}
        self._drain_seq = 0
        self._dec = dataclasses.replace(
            model, decode=True, paged=True, page_size=self.page_size,
            num_pages=self.num_pages, cache_len=None, name=None)
        self._jax = jax

        def _apply(params, cache, tokens, positions, page_table):
            logits, mut = self._dec.apply(
                {"params": params, "cache": cache}, tokens,
                positions=positions, page_table=page_table,
                mutable=["cache"])
            return logits, mut["cache"]

        self._apply = jax.jit(_apply)
        self._cache = None  # built lazily from shapes on first weights
        self._step_count = 0

        # --- speculative decoding: a small draft model riding the same
        # weight chain. The default draft is the target truncated to its
        # first `draft_depth` blocks — block names are positional
        # (`block0`..`block{d-1}`), so the draft's parameters are a pure
        # SUBSET of every published tree and a new generation fences
        # draft + target together for free.
        self.spec_lookahead = int(
            spec_lookahead if spec_lookahead is not None
            else _env_int(SPEC_LOOKAHEAD_ENV, 4))
        d = int(draft_depth if draft_depth is not None
                else _env_int(SPEC_DRAFT_DEPTH_ENV, 0))
        self._draft_model = draft_model
        if self._draft_model is None and d > 0:
            if d > int(model.depth):
                raise ValueError(
                    f"draft_depth {d} exceeds the target model's depth "
                    f"{model.depth}")
            self._draft_model = dataclasses.replace(
                model, depth=d, name=None)
        self._draft_arms: Dict[str, _Arm] = {}
        self._draft_cache = None
        self._draft_param_shapes = None
        if self._draft_model is not None:
            if self.spec_lookahead < 1:
                raise ValueError(
                    f"spec_lookahead must be >= 1 with a draft model, "
                    f"got {self.spec_lookahead}")
            self._draft_dec = dataclasses.replace(
                self._draft_model, decode=True, paged=True,
                page_size=self.page_size, num_pages=self.num_pages,
                cache_len=None, name=None)

            def _draft_apply(params, cache, tokens, positions,
                             page_table):
                logits, mut = self._draft_dec.apply(
                    {"params": params, "cache": cache}, tokens,
                    positions=positions, page_table=page_table,
                    mutable=["cache"])
                return logits, mut["cache"]

            self._draft_apply = jax.jit(_draft_apply)

    # ------------------------------------------------------------- weights

    def _arm_namespace(self, arm: str) -> Optional[int]:
        """Prefix-cache namespace for `arm`: the weight generation its
        sequences decode under. Cached KV is only reusable under the
        exact weights that wrote it — aliasing across generations would
        silently mix models. None (arm not installed) disables caching
        for the request."""
        a = self._arms.get(arm)
        return None if a is None else int(a.generation)

    def set_weights(self, tree: Any, *, generation: int = 0,
                    arm: str = "stable") -> None:
        """Install a weight tree for `arm` (device-resident; a host tree
        is moved once here, not per step). Trees shaped like a loop state
        (``{"params": ...}``) are unwrapped the same way the publisher's
        ``extract`` does."""
        import jax.numpy as jnp

        from horovod_tpu.serving.publisher import default_extract

        params = self._jax.tree_util.tree_map(
            jnp.asarray, default_extract(tree))
        if self.tp_axis:
            params = self._tp_place_params(params)
        self._park_if_busy(arm)
        self._arms[arm] = _Arm(int(generation), params)
        if self._cache is None:
            self._init_cache()
        if self._draft_model is not None:
            # draft rides the same chain: every published generation
            # derives its draft at install time, so draft and target
            # can never be fenced apart by the rollout state machine
            self._draft_arms[arm] = _Arm(
                int(generation), self._subset_draft_params(params))
            if self._draft_cache is None:
                self._init_draft_cache()
        if _metrics.enabled():
            _metrics.gauge(
                "serving_engine_generation",
                help="weight generation each rollout arm serves",
                arm=arm,
            ).set(int(generation))

    def set_draft_weights(self, tree: Any, *, generation: int = 0,
                          arm: str = "stable") -> None:
        """Install draft params for `arm` explicitly (tests and callers
        publishing the draft separately). Speculative decoding only runs
        while the draft's generation matches the target arm's — a
        lagging draft silently falls back to plain decode rather than
        ever verifying a canary against stale proposals."""
        import jax.numpy as jnp

        from horovod_tpu.serving.publisher import default_extract

        if self._draft_model is None:
            raise ValueError(
                "engine has no draft model (set draft_depth or "
                f"{SPEC_DRAFT_DEPTH_ENV})")
        params = self._jax.tree_util.tree_map(
            jnp.asarray, default_extract(tree))
        self._draft_arms[arm] = _Arm(
            int(generation), self._subset_draft_params(params))
        if self._draft_cache is None:
            self._init_draft_cache()

    def _subset_draft_params(self, params: Any) -> Any:
        """Project a full target tree onto the draft's parameter
        structure (token/position embeddings, the first `draft_depth`
        blocks, final LN, LM head — all shared names)."""
        if self._draft_param_shapes is None:
            import jax
            import jax.numpy as jnp

            b, c = self.max_batch, self.prefill_chunk
            self._draft_param_shapes = jax.eval_shape(
                self._draft_dec.init, jax.random.PRNGKey(0),
                jnp.zeros((b, c), jnp.int32),
                positions=jnp.zeros((b, c), jnp.int32),
                page_table=jnp.zeros(
                    (b, self.pages_per_seq), jnp.int32),
            )["params"]

        def take(shape_node, full_node, path=""):
            if hasattr(shape_node, "items"):
                try:
                    return {k: take(v, full_node[k], f"{path}/{k}")
                            for k, v in shape_node.items()}
                except (KeyError, TypeError):
                    raise ValueError(
                        f"draft model needs parameter subtree {path!r} "
                        f"the published tree does not carry — the draft "
                        f"must be a truncation of the target") from None
            if tuple(getattr(full_node, "shape", ())) \
                    != tuple(shape_node.shape):
                raise ValueError(
                    f"draft parameter {path!r} expects shape "
                    f"{tuple(shape_node.shape)}, published tree carries "
                    f"{tuple(getattr(full_node, 'shape', ()))} — the "
                    f"draft must be a truncation of the target")
            return full_node

        return take(self._draft_param_shapes, params)

    def arm_generation(self, arm: str) -> Optional[int]:
        a = self._arms.get(arm)
        return None if a is None else a.generation

    def arm_params(self, arm: str) -> Optional[Any]:
        a = self._arms.get(arm)
        return None if a is None else a.params

    def _park_if_busy(self, arm: str) -> None:
        """An arm being replaced while it still has in-flight sequences
        parks its old params under a private drain label — a sequence's
        KV cache was built under its weights, so swapping them mid-decode
        would emit incoherent tokens. The parked arm releases itself at
        the step boundary its last sequence finishes."""
        old = self._arms.get(arm)
        if old is None or not self._sched.active(arm):
            return
        self._drain_seq += 1  # unique label even if the same (arm,
        # generation) parks twice across vetoes
        label = f"{arm}-drain-{self._drain_seq}-g{old.generation}"
        old.draining = True
        self._arms[label] = old
        # the draft parks alongside its target: draining sequences keep
        # speculating on the generation they decode under
        od = self._draft_arms.get(arm)
        if od is not None:
            self._draft_arms[label] = od
        moved = self._sched.move_active_to_drain(arm, label)
        logger.info(
            "arm %r replaced with %d sequence(s) in flight; draining "
            "them on generation %d as %r", arm, moved, old.generation,
            label)

    def promote_canary(self) -> None:
        """Canary becomes stable (the rollout controller's promotion).
        In-flight canary sequences are relabeled — the params they decode
        against ARE the promoted ones, so their tokens are unaffected and
        they must not be stranded on an arm that no longer exists. The
        OLD stable arm's in-flight sequences keep their own weights: they
        park under a drain label and finish coherently."""
        arm = self._arms.pop("canary", None)
        if arm is None:
            return
        darm = self._draft_arms.pop("canary", None)
        self._park_if_busy("stable")
        arm.draining = False
        self._arms["stable"] = arm
        if darm is not None:
            self._draft_arms["stable"] = darm
        else:
            # the promoted generation has no draft: leaving the old
            # stable draft behind would fence-fail anyway; drop it
            self._draft_arms.pop("stable", None)
        self._sched.relabel_arm("canary", "stable")
        if _metrics.enabled():
            _metrics.gauge(
                "serving_engine_generation",
                help="weight generation each rollout arm serves",
                arm="stable",
            ).set(arm.generation)

    def retire_arm(self, arm: str) -> None:
        """Stop routing to `arm` but keep its params until every in-flight
        sequence on it finished — a rollback never drops work mid-decode.
        Requests still *queued* for the arm have produced no tokens yet,
        so they simply re-route to stable."""
        a = self._arms.get(arm)
        if a is not None:
            a.draining = True
        if arm != "stable":
            self._sched.relabel_queued_only(arm, "stable")

    def poll_weights(self) -> Optional[int]:
        """Standalone (no rollout controller) weight refresh: poll the
        attached subscriber into the stable arm and feed the health
        plane. Returns the new generation when one arrived."""
        if self._subscriber is None:
            return None
        fresh = self._subscriber.poll()
        note_subscriber_health(self._subscriber)
        if fresh is None:
            return None
        gen = self._subscriber.generation
        self.set_weights(fresh, generation=gen, arm="stable")
        return gen

    def _init_cache(self) -> None:
        import jax
        import jax.numpy as jnp

        b, c = self.max_batch, self.prefill_chunk
        shapes = jax.eval_shape(
            self._dec.init, jax.random.PRNGKey(0),
            jnp.zeros((b, c), jnp.int32),
            positions=jnp.zeros((b, c), jnp.int32),
            page_table=jnp.zeros((b, self.pages_per_seq), jnp.int32),
        )["cache"]
        self._cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self.tp_axis:
            self._cache = self._tp_place_cache(self._cache)

    def _tp_place_params(self, params: Any) -> Any:
        """Shard a param tree over the tp axis with the Megatron layouts
        from :func:`~horovod_tpu.models.transformer.transformer_param_specs`
        (qkv/mlp_up column-split, proj/mlp_down row-split → one psum per
        pair, inserted by the partitioner)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from horovod_tpu.models.transformer import transformer_param_specs

        specs = transformer_param_specs(params, model_axis=self.tp_axis)
        tp = self._mesh.shape[self.tp_axis]

        def place(x, s):
            # a spec'd dim the axis size does not divide (typically the
            # vocab dim of lm_head/tok_embed) stays replicated — the same
            # indivisible-leaf policy as training's _shard_dim0_tree
            for i, name in enumerate(s):
                if name is not None and x.shape[i] % tp != 0:
                    s = PartitionSpec()
                    break
            return jax.device_put(x, NamedSharding(self._mesh, s))

        return jax.tree_util.tree_map(place, params, specs)

    def _tp_place_cache(self, cache: Any) -> Any:
        """Head-shard the page pools ``[P, page_size, H_kv, D]`` on dim 2
        so each rank's decode attention touches only its own heads."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self._mesh, P(None, None, self.tp_axis, None))
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), cache)

    def _init_draft_cache(self) -> None:
        import jax
        import jax.numpy as jnp

        b, c = self.max_batch, self.prefill_chunk
        shapes = jax.eval_shape(
            self._draft_dec.init, jax.random.PRNGKey(0),
            jnp.zeros((b, c), jnp.int32),
            positions=jnp.zeros((b, c), jnp.int32),
            page_table=jnp.zeros((b, self.pages_per_seq), jnp.int32),
        )["cache"]
        self._draft_cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self.tp_axis:
            self._draft_cache = self._tp_place_cache(self._draft_cache)

    # ------------------------------------------------------------ requests

    def submit(self, req_or_prompt, max_new_tokens: Optional[int] = None,
               *, rid=None, temperature: float = 0.0,
               arm: str = "stable") -> Request:
        """Queue a request (a prebuilt :class:`Request` or a prompt
        array). Raises :class:`QueueFull` under admission backpressure and
        ``ValueError`` for prompts that can never fit one sequence's page
        budget."""
        if isinstance(req_or_prompt, Request):
            req = req_or_prompt
        else:
            if max_new_tokens is None:
                raise ValueError("submit(prompt) needs max_new_tokens")
            req = Request(
                rid if rid is not None else f"req-{id(req_or_prompt)}",
                req_or_prompt, max_new_tokens, temperature=temperature,
                arm=arm)
        total = req.prompt.size + req.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"request {req.rid!r}: prompt + max_new_tokens = {total} "
                f"exceeds max_seq_len {self.max_seq_len}")
        self._sched.submit(req)
        return req

    @property
    def scheduler(self) -> ContinuousBatchingScheduler:
        return self._sched

    # ---------------------------------------------------------- iteration

    def step(self) -> bool:
        """One iteration boundary: chaos intake → admission → one chunked
        prefill pass, one speculative pass, and one decode pass per
        active arm. Returns True when any compute ran (False = fully
        idle)."""
        self._step_count += 1
        self._chaos_burst()
        if _chaos.take_cache_evict(self._step_count):
            victims, dropped = self._sched.chaos_evict()
            logger.warning(
                "chaos cache_evict at pass %d: dropped %d cached "
                "page(s), %d victim sequence(s) re-prefilling",
                self._step_count, dropped, victims)
        if not self._arms:
            return False  # no weights yet; requests keep queueing
        self._sched.admit()
        ran = False
        for arm in self._sched.arms_active():
            a = self._arms.get(arm)
            if a is None:
                for seq in self._sched.active(arm):
                    self._sched.finish(
                        seq, error=f"no weights for arm {arm!r}")
                continue
            ran |= self._prefill_pass(arm, a)
            handled = self._spec_pass(arm, a)
            ran |= bool(handled)
            ran |= self._decode_pass(arm, a, exclude=handled)
        # a retired arm with nothing left in flight releases its params
        for name in [n for n, a in self._arms.items() if a.draining]:
            if not self._sched.active(name):
                del self._arms[name]
                self._draft_arms.pop(name, None)
        return ran

    def run_until_idle(self, max_iters: int = 10000) -> None:
        """Drive :meth:`step` until queue and slots are empty (tests and
        batch-style callers); raises past `max_iters` instead of spinning
        forever on a scheduling bug."""
        for _ in range(max_iters):
            if self._sched.idle():
                return
            if not self._arms:
                raise RuntimeError(
                    "engine has work queued but no weights installed — "
                    "call set_weights() or poll_weights() first")
            self.step()
        raise RuntimeError(
            f"engine did not drain within {max_iters} iterations")

    def _chaos_burst(self) -> None:
        """``HOROVOD_CHAOS=request_burst=N``: N synthetic requests slam
        the queue at one iteration boundary — the deterministic
        queue-overflow drill. Rejections are the point; they are counted
        by admission control."""
        n = _chaos.take_request_burst()
        for i in range(n):
            try:
                self.submit(
                    Request(f"chaos-burst-{i}", [1, 1], 1))
            except (QueueFull, ValueError) as e:
                logger.debug("chaos burst request rejected: %s", e)

    # ------------------------------------------------------------- passes

    def _maybe_slow(self, arm: str) -> None:
        """``HOROVOD_CHAOS=slow_decode=<s>[:<arm>[@<replica>]]``: sleep
        before this pass when the charge targets `arm` (drain labels
        inherit their source arm's scope) and, when a ``@<replica>``
        suffix is present, only on the engine whose fleet ``replica`` id
        matches — the deterministic latency regression, scopeable to one
        replica's canary arm for fleet-rollback drills. Host-side only:
        tokens are unaffected, so a drill keeps token parity with a
        clean run."""
        charge = _chaos.slow_decode()
        if charge is None:
            return
        secs, target = charge
        if secs <= 0:
            return
        if target is not None:
            base, _, rep = target.partition("@")
            if rep and rep != (self.replica or ""):
                return
            if (base and arm != base
                    and not arm.startswith(f"{base}-drain")):
                return
        _chaos.record_injection("slow_decode")
        time.sleep(secs)

    def _run(self, params, tokens, positions, table, kind: str):
        import jax.numpy as jnp

        logits, self._cache = self._apply(
            params, self._cache, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(table))
        if _metrics.enabled():
            _metrics.counter(
                "serving_engine_steps",
                help="compiled engine iterations, by phase",
                kind=kind,
            ).inc()
        return np.asarray(logits)

    def _run_draft(self, params, tokens, positions, table, kind: str):
        import jax.numpy as jnp

        logits, self._draft_cache = self._draft_apply(
            params, self._draft_cache, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(table))
        if _metrics.enabled():
            _metrics.counter(
                "serving_engine_steps",
                help="compiled engine iterations, by phase",
                kind=kind,
            ).inc()
        return np.asarray(logits)

    def _prefill_pass(self, arm: str, a: _Arm) -> bool:
        rows = [s for s in self._sched.active(arm) if s.prefilling]
        if not rows:
            return False
        self._maybe_slow(arm)
        t0 = time.monotonic()
        b, c = self.max_batch, self.prefill_chunk
        tokens = np.zeros((b, c), np.int32)
        positions = np.zeros((b, c), np.int32)
        table = np.zeros((b, self.pages_per_seq), np.int32)  # trash rows
        real_table = self._sched.page_table_rows()
        rems: List[int] = []
        for s in rows:
            # prefill_src is the prompt, or prompt + replayed generated
            # tokens after a forced cache eviction; a prefix-cache hit
            # pre-advanced done_prompt past the aliased pages
            rem = min(c, s.prefill_len - s.done_prompt)
            tokens[s.slot, :rem] = s.prefill_src[
                s.done_prompt:s.done_prompt + rem]
            positions[s.slot] = s.done_prompt + np.arange(c, dtype=np.int32)
            table[s.slot] = real_table[s.slot]
            rems.append(rem)
        logits = self._run(a.params, tokens, positions, table, "prefill")
        da = self._draft_arms.get(arm)
        if da is not None:
            # mirror the writes into the draft cache so proposals can
            # attend to the prompt (same tokens, positions, tables)
            self._run_draft(da.params, tokens, positions, table,
                            "draft_prefill")
        if _metrics.enabled():
            _metrics.counter(
                "serving_prefill_tokens",
                help="prompt tokens written to the paged cache",
            ).inc(sum(rems))
        for s, rem in zip(rows, rems):
            s.done_prompt += rem
            _reqtrace.on_prefill_chunk(s, rem, t0, a.generation)
            if s.done_prompt >= s.prefill_len and not s.generated:
                # the row's first sampled token comes from ITS last real
                # position in this chunk, exactly like generate()'s
                # last_logits gather. A replay (post-eviction rebuild)
                # with tokens already sampled consumes nothing: its
                # next token resumes from last_token in the decode pass.
                self._consume_logits(s, logits[s.slot, rem - 1],
                                     a.generation)
        return True

    def _spec_pass(self, arm: str, a: _Arm) -> set:
        """Speculative decode for every eligible row: the draft proposes
        ``spec_lookahead`` greedy tokens (K single-token forwards on its
        own paged cache), the target verifies all of them in ONE
        ``[b, K+1]`` forward, and the longest agreeing prefix plus the
        target's own next token are emitted. Greedy acceptance makes the
        emitted stream token-identical to sequential decode by
        construction: every emitted token is the target's argmax given
        exactly the tokens before it. A rejected tail costs nothing to
        roll back — its KV sits past the row's frontier, where
        paged_decode_attention zeroes before the matmuls, and the next
        writes overwrite it.

        Eligible: greedy rows with at least K+1 tokens of budget left
        (the verify forward must stay inside the page reservation), on
        an arm whose draft generation MATCHES the target's — a stale
        draft falls back to plain decode, never a canary verifying
        against old proposals. Returns the ids of handled sequences."""
        handled: set = set()
        if self._draft_model is None:
            return handled
        da = self._draft_arms.get(arm)
        if da is None or da.generation != a.generation:
            return handled
        K = self.spec_lookahead
        rows = [s for s in self._sched.active(arm)
                if not s.prefilling and s.last_token is not None
                and s.req.temperature <= 0.0
                and s.req.max_new_tokens - len(s.generated) >= K + 1]
        if not rows:
            return handled
        self._maybe_slow(arm)
        b = self.max_batch
        real_table = self._sched.page_table_rows()
        table = np.zeros((b, self.pages_per_seq), np.int32)
        base: Dict[int, int] = {}
        for s in rows:
            table[s.slot] = real_table[s.slot]
            base[id(s)] = s.length
        # --- proposal: K sequential draft forwards (writes the draft's
        # own KV as it goes, so token j attends to tokens < j)
        drafts = np.zeros((b, K), np.int32)
        cur = np.zeros((b, 1), np.int32)
        pos = np.zeros((b, 1), np.int32)
        for s in rows:
            cur[s.slot, 0] = s.last_token
            pos[s.slot, 0] = base[id(s)]
        for j in range(K + 1):
            dl = self._run_draft(da.params, cur, pos, table,
                                 "draft_propose")
            # the K+1'th forward only WRITES d_K's draft KV (logits
            # unused): on full acceptance the next round's frontier sits
            # past it, and a draft cache hole there would desync the
            # draft from the target — rejected tails need no such care,
            # they are masked then overwritten
            if j < K:
                for s in rows:
                    nxt = int(np.argmax(dl[s.slot, 0]))
                    drafts[s.slot, j] = nxt
                    cur[s.slot, 0] = nxt
            pos = pos + 1
        # --- verify: ONE batched [b, K+1] target forward over
        # [last_token, d_1 .. d_K]; row i's logits are the target's
        # next-token distribution after the first i+1 of those
        vtok = np.zeros((b, K + 1), np.int32)
        vpos = np.zeros((b, K + 1), np.int32)
        for s in rows:
            vtok[s.slot, 0] = s.last_token
            vtok[s.slot, 1:] = drafts[s.slot]
            vpos[s.slot] = base[id(s)] + np.arange(K + 1, dtype=np.int32)
        logits = self._run(a.params, vtok, vpos, table, "spec_verify")
        for s in rows:
            handled.add(id(s))
            row = logits[s.slot]  # [K+1, vocab]
            m = 0
            while (m < K and np.all(np.isfinite(row[m]))
                   and int(np.argmax(row[m])) == int(drafts[s.slot, m])):
                m += 1
            # emit the m accepted tokens plus the target's bonus token
            # at the first divergence (sequential-greedy semantics: stop
            # early if the sequence finishes on budget/EOS/non-finite)
            for i in range(m + 1):
                self._consume_logits(s, row[i], a.generation)
                if s.req.done:
                    break
            if _metrics.enabled():
                _metrics.counter(
                    "spec_proposed",
                    help="draft tokens proposed to the target verifier",
                ).inc(K)
                _metrics.counter(
                    "spec_accepted",
                    help="draft tokens the target verifier accepted",
                ).inc(m)
                if m < K:
                    _metrics.counter(
                        "spec_rollbacks",
                        help="speculative iterations whose tail was "
                             "rejected (frontier rolled back)",
                    ).inc()
            _reqtrace.on_spec_verify(s, K, m, a.generation)
        return handled

    def _decode_pass(self, arm: str, a: _Arm,
                     exclude: Optional[set] = None) -> bool:
        rows = [s for s in self._sched.active(arm)
                if not s.prefilling and s.last_token is not None
                and (exclude is None or id(s) not in exclude)]
        if not rows:
            return False
        self._maybe_slow(arm)
        b = self.max_batch
        tokens = np.zeros((b, 1), np.int32)
        positions = np.zeros((b, 1), np.int32)
        table = np.zeros((b, self.pages_per_seq), np.int32)
        real_table = self._sched.page_table_rows()
        for s in rows:
            tokens[s.slot, 0] = s.last_token
            positions[s.slot, 0] = s.length
            table[s.slot] = real_table[s.slot]
        logits = self._run(a.params, tokens, positions, table, "decode")
        for s in rows:
            self._consume_logits(s, logits[s.slot, 0], a.generation)
        return True

    def _consume_logits(self, s, row_logits: np.ndarray,
                        generation: int = -1) -> None:
        """Sample one token for `s` from its ``[vocab]`` logits row and
        retire the sequence when it is done (budget reached, EOS, or
        non-finite logits — the canary regression signal)."""
        if not np.all(np.isfinite(row_logits)):
            self._sched.finish(seq=s, error="non-finite logits")
            return
        first = not s.generated
        tok = s.sample(row_logits)
        s.generated.append(tok)
        s.last_token = tok
        # TTFT closes on the first sampled token; every later one is a
        # TPOT cadence point — tagged with the weight generation that
        # actually decoded it, so gate windows never mix generations
        if first:
            _reqtrace.on_first_token(s, generation)
        else:
            _reqtrace.on_token(s, generation)
        if _metrics.enabled():
            _metrics.counter(
                "serving_tokens_generated",
                help="tokens sampled by the engine",
            ).inc()
        if (len(s.generated) >= s.req.max_new_tokens
                or (self.eos_token is not None and tok == self.eos_token)):
            self._sched.finish(seq=s)
