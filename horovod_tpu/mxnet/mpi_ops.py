"""MXNet collective ops (reference ``horovod/mxnet/mpi_ops.py:60-242``).

The reference pushes async engine ops through ``horovod_mxnet_*_async`` C
entry points; here the ops bridge NDArray-like tensors to the XLA collective
layer (:mod:`horovod_tpu.ops.collective`). Tensors are duck-typed: anything
with ``.asnumpy()`` (mxnet NDArray) or convertible via ``np.asarray`` works,
and in-place variants write back with ``tensor[:] = ...`` — so the logic is
exercisable without an mxnet install (Apache MXNet is retired upstream and
absent from the TPU image).

``priority`` is accepted for API parity; execution order is XLA's concern
here (the reference maps it to ``FnProperty::kCPUPrioritized`` in its engine,
``mxnet/mpi_ops.cc:67-110``).
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, mpi_threads_supported,
    nccl_built, mpi_built, gloo_built, ccl_built, ddl_built, xla_built,
)
from horovod_tpu.ops import collective as C
from horovod_tpu.ops.collective import (  # noqa: F401
    Adasum, Average, ReduceOp, Sum,
)


def _to_np(tensor):
    if hasattr(tensor, "asnumpy"):
        return tensor.asnumpy()
    return np.asarray(tensor)


def _wrap_like(tensor, out_np):
    """Return `out_np` as the same kind of array as `tensor`."""
    if hasattr(tensor, "asnumpy"):  # mxnet NDArray
        import mxnet as mx  # pragma: no cover - mxnet not in image

        return mx.nd.array(out_np, ctx=tensor.context, dtype=out_np.dtype)
    return out_np


def allreduce(tensor, average=True, name=None, priority=0):
    """Allreduce returning a new tensor (reference ``mpi_ops.py:60-91``)."""
    del priority
    out = C.allreduce(
        _to_np(tensor),
        C.Average if average else C.Sum,
        name=None if name is None else f"mx.allreduce.{name}",
    )
    return _wrap_like(tensor, np.asarray(out))


def allreduce_(tensor, average=True, name=None, priority=0):
    """In-place allreduce (reference ``mpi_ops.py:94-129``)."""
    del priority
    out = C.allreduce(
        _to_np(tensor),
        C.Average if average else C.Sum,
        name=None if name is None else f"mx.allreduce.{name}",
    )
    tensor[:] = np.asarray(out)
    return tensor


def allgather(tensor, name=None, priority=0):
    """Concatenate per-rank tensors along dim 0 (reference
    ``mpi_ops.py:132-170``)."""
    del priority
    out = C.allgather(
        _to_np(tensor),
        name=None if name is None else f"mx.allgather.{name}",
    )
    return _wrap_like(tensor, np.asarray(out))


def broadcast(tensor, root_rank, name=None, priority=0):
    """Broadcast returning a new tensor (reference ``mpi_ops.py:173-207``)."""
    del priority
    out = C.broadcast(
        _to_np(tensor), root_rank,
        name=None if name is None else f"mx.broadcast.{name}",
    )
    return _wrap_like(tensor, np.asarray(out))


def broadcast_(tensor, root_rank, name=None, priority=0):
    """In-place broadcast (reference ``mpi_ops.py:210-242``)."""
    del priority
    out = C.broadcast(
        _to_np(tensor), root_rank,
        name=None if name is None else f"mx.broadcast.{name}",
    )
    tensor[:] = np.asarray(out)
    return tensor
