"""MXNet frontend: ``import horovod_tpu.mxnet as hvd``.

Reference parity target: ``horovod/mxnet/__init__.py`` + ``mxnet/mpi_ops.py``
(0.19.2) — ``DistributedOptimizer`` allreducing in ``update()``, gluon
``DistributedTrainer`` with rescaled gradients, ``broadcast_parameters``.

MXNet is not in the TPU image (Apache MXNet is retired upstream), so the
module gates at import: every symbol raises with the parity note. The engine
underneath (collectives, launcher, optimizer-wrapper pattern) is
framework-agnostic — see :mod:`horovod_tpu.torch` for the identical surface
on a live framework; porting this file to a working mxnet install is the
torch file with gluon naming."""

from __future__ import annotations

try:
    import mxnet  # noqa: F401

    _HAVE_MXNET = True
except ImportError:
    _HAVE_MXNET = False

from horovod_tpu.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, is_homogeneous, mpi_threads_supported,
    nccl_built, mpi_built, gloo_built, ccl_built, ddl_built, xla_built,
)
from horovod_tpu.ops.collective import (  # noqa: F401
    Adasum, Average, ReduceOp, Sum,
)


def _need_mxnet(name):
    raise ImportError(
        f"horovod_tpu.mxnet.{name} needs mxnet, which is not installed "
        "(upstream Apache MXNet is retired; reference "
        "horovod/mxnet/__init__.py). The same surface is live for torch: "
        "horovod_tpu.torch"
    )


if _HAVE_MXNET:  # pragma: no cover - mxnet not in image
    raise NotImplementedError(
        "mxnet detected but the gluon frontend is not wired; port "
        "horovod_tpu/torch/__init__.py (reference horovod/mxnet/)"
    )


def DistributedOptimizer(*a, **k):
    """Reference ``horovod/mxnet/__init__.py:DistributedOptimizer``."""
    _need_mxnet("DistributedOptimizer")


def DistributedTrainer(*a, **k):
    """Reference gluon ``DistributedTrainer`` (``mxnet/__init__.py``)."""
    _need_mxnet("DistributedTrainer")


def broadcast_parameters(*a, **k):
    """Reference ``horovod/mxnet/__init__.py:broadcast_parameters``."""
    _need_mxnet("broadcast_parameters")


def allreduce(*a, **k):
    _need_mxnet("allreduce")


def allgather(*a, **k):
    _need_mxnet("allgather")


def broadcast(*a, **k):
    _need_mxnet("broadcast")
