"""MXNet frontend: ``import horovod_tpu.mxnet as hvd``.

Reference parity with ``horovod/mxnet/__init__.py`` + ``mxnet/mpi_ops.py``
(0.19.2): a ``DistributedOptimizer`` that allreduces gradients inside
``update()``/``update_multi_precision()``, a gluon ``DistributedTrainer``
whose ``_allreduce_grads`` replaces kvstore push/pull, and
``broadcast_parameters`` with deferred-initialization hooks.

Apache MXNet is retired upstream and not in the TPU image, so everything
here is duck-typed against the small mxnet surface it touches (optimizer
``update``/``rescale_grad``, trainer ``_params``/``_scale``, parameter
``list_grad``/``grad_req``) and the collective bridge accepts any
NDArray-like (:mod:`horovod_tpu.mxnet.mpi_ops`). With mxnet installed the
gluon ``DistributedTrainer`` subclass is created dynamically; without it the
same logic is importable and tested through fakes (``tests/test_mxnet.py``).
"""

from __future__ import annotations

import types
import warnings

from horovod_tpu.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, is_homogeneous, mpi_threads_supported,
    mpi_enabled, gloo_enabled,
    nccl_built, mpi_built, gloo_built, ccl_built, ddl_built, xla_built,
)
from horovod_tpu.mxnet.mpi_ops import (  # noqa: F401
    Adasum, Average, ReduceOp, Sum,
    allgather, allreduce, allreduce_, broadcast, broadcast_,
)

try:  # pragma: no cover - mxnet not in the TPU image
    import mxnet as mx

    _HAVE_MXNET = True
except ImportError:
    mx = None
    _HAVE_MXNET = False


class DistributedOptimizer:
    """Optimizer wrapper allreducing gradients in ``update()`` (reference
    ``horovod/mxnet/__init__.py:40-78``): ``rescale_grad`` is divided by
    ``size()`` so the summed allreduce averages — cheaper than dividing the
    reduced tensor."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._optimizer.rescale_grad /= size()

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_allreduce(self, index, grad):
        if size() == 1:
            return
        if isinstance(index, (tuple, list)):
            for i in range(len(index)):
                allreduce_(
                    grad[i], average=False, name=str(index[i]), priority=-i
                )
        else:
            allreduce_(grad, average=False, name=str(index))

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


class _TrainerAllreduceMixin:
    """The gluon ``DistributedTrainer`` override logic, separated from the
    ``mx.gluon.Trainer`` base so it is testable without mxnet: allreduce
    (sum) every parameter's gradient; averaging rides the trainer's
    ``_scale / size()`` rescale (reference ``mxnet/__init__.py:85-112``)."""

    def _allreduce_grads(self):
        if size() == 1:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                allreduce_(
                    param.list_grad()[0], average=False,
                    name=param.name, priority=-i,
                )


def DistributedTrainer(params, optimizer, optimizer_params=None):
    """gluon Trainer whose gradient exchange is the allreduce layer instead
    of kvstore push/pull (reference ``mxnet/__init__.py:85-112``)."""
    if not _HAVE_MXNET:  # pragma: no cover - exercised via fakes in tests
        raise ImportError(
            "DistributedTrainer needs mxnet (retired upstream; not in the "
            "TPU image). The override logic lives in _TrainerAllreduceMixin "
            "and is tested through fakes."
        )
    if isinstance(optimizer, DistributedOptimizer):
        optimizer = optimizer._optimizer
        warnings.warn(
            "DistributedTrainer does not take DistributedOptimizer as its "
            "optimizer. We have unwrapped it for you."
        )
    cls = type(
        "DistributedTrainer", (_TrainerAllreduceMixin, mx.gluon.Trainer), {}
    )
    trainer = cls(
        params, optimizer, optimizer_params=optimizer_params, kvstore=None
    )
    # summed allreduce + scale/size == average (reference comment)
    trainer._scale /= size()
    return trainer


def _append_broadcast_init(param, root_rank):
    """Wrap a parameter's ``_init_impl`` so deferred-initialized parameters
    broadcast right after they materialize (reference
    ``mxnet/__init__.py:115-121``)."""
    init_impl = getattr(param, "_init_impl")

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank=root_rank, name=self.name)

    return wrapped_init_impl


def broadcast_parameters(params, root_rank=0):
    """Broadcast parameters from `root_rank` (reference
    ``mxnet/__init__.py:124-155``). Accepts a dict of name -> NDArray-like,
    or a gluon ``ParameterDict`` (deferred initialization handled via an
    ``_init_impl`` hook)."""
    if size() == 1:
        return

    tensors, names = [], []
    if isinstance(params, dict):
        names, tensors = zip(*sorted(params.items())) if params else ((), ())
    elif _HAVE_MXNET and isinstance(
        params, mx.gluon.parameter.ParameterDict
    ):  # pragma: no cover - mxnet not in image
        for name, p in sorted(params.items()):
            try:
                tensors.append(p.data())
                names.append(name)
            except mx.gluon.parameter.DeferredInitializationError:
                p._init_impl = types.MethodType(
                    _append_broadcast_init(p, root_rank), p
                )
    else:
        raise ValueError(f"invalid params of type: {type(params)}")

    for tensor, name in zip(tensors, names):
        broadcast_(tensor, root_rank, name=str(name))
