"""Metric exporters: Prometheus text exposition, JSON snapshot, and the
opt-in rank-0 HTTP endpoint.

stdlib only (see the package docstring). The HTTP server is a plain
``http.server`` on a daemon thread — scraping a training job must never
require a new dependency — started by :func:`maybe_start_http_server` when
``HOROVOD_METRICS_PORT`` is set (``horovod_tpu.init`` calls it on process
rank 0 only, mirroring the reference's coordinator-only Timeline).

Endpoints:

- ``/metrics`` — Prometheus text exposition format (scrape target)
- ``/metrics.json`` — the raw :func:`metrics.snapshot` as JSON
- ``/health`` — the resilience health-state-machine snapshot as JSON
  (HTTP 200 while HEALTHY/SUSPECT, 503 once DEGRADED or FATAL, so a plain
  liveness probe needs no JSON parsing)
"""

from __future__ import annotations

import json
import math
import re
import threading
from typing import Optional

from horovod_tpu.observability import metrics as _metrics

__all__ = [
    "to_prometheus",
    "to_json",
    "emit_snapshot",
    "start_http_server",
    "stop_http_server",
    "maybe_start_http_server",
]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _quote_label_value(v) -> str:
    """One label value escaped AND quoted per the exposition format:
    backslash, double quote, and newline (a raw newline inside a label
    value terminates the sample line mid-way and corrupts the whole scrape
    — every series after it is misparsed). The single escape point — the
    fleet exporter builds its ``stat=``/``rank=`` pairs through it too."""
    v = (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )
    return f'"{v}"'


def _prom_labels(key: str, extra: Optional[str] = None) -> str:
    """``"k=v,k2=v2"`` snapshot label key -> ``{k="v",k2="v2"}`` (empty
    string for no labels). ``extra`` is a pre-formatted ``le="..."`` pair."""
    pairs = []
    if key:
        for item in key.split(","):
            k, _, v = item.partition("=")
            pairs.append(
                f'{_LABEL_NAME_RE.sub("_", k)}={_quote_label_value(v)}'
            )
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt(v: float) -> str:
    f = float(v)
    if not math.isfinite(f):  # exposition spellings; int(inf) would raise
        return "NaN" if math.isnan(f) else ("+Inf" if f > 0 else "-Inf")
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def to_prometheus(snap: Optional[dict] = None) -> str:
    """Render a snapshot in Prometheus text exposition format (one
    ``# HELP``/``# TYPE`` header per family; histogram children expand to
    ``_bucket{le=...}``/``_sum``/``_count`` series)."""
    snap = _metrics.snapshot() if snap is None else snap
    lines = []
    for name in sorted(snap):
        fam = snap[name]
        pname = _prom_name(name)
        if fam.get("help"):
            esc = fam["help"].replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {pname} {esc}")
        lines.append(f"# TYPE {pname} {fam['type']}")
        for key in sorted(fam["samples"]):
            sample = fam["samples"][key]
            if fam["type"] == "histogram":
                for le, cum in sample["buckets"].items():
                    extra = 'le="' + le + '"'
                    lines.append(
                        f"{pname}_bucket{_prom_labels(key, extra)} {cum}"
                    )
                lines.append(
                    f"{pname}_sum{_prom_labels(key)} {_fmt(sample['sum'])}"
                )
                lines.append(
                    f"{pname}_count{_prom_labels(key)} {sample['count']}"
                )
            else:
                lines.append(f"{pname}{_prom_labels(key)} {_fmt(sample)}")
    return "\n".join(lines) + "\n" if lines else ""


def to_json(snap: Optional[dict] = None, *, indent: Optional[int] = None) -> str:
    """The snapshot as a JSON document (what ``MetricsCallback`` dumps and
    ``/metrics.json`` serves)."""
    return json.dumps(
        _metrics.snapshot() if snap is None else snap, indent=indent
    )


def emit_snapshot(dump_path: Optional[str], printer, header: str = "") -> None:
    """Shared emit step for the ``MetricsCallback`` twins: atomically write
    the JSON snapshot to ``dump_path`` when set, otherwise print the
    summary (prefixed with ``header``) through ``printer``."""
    import os

    if dump_path:
        tmp = dump_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(to_json(indent=1))
        os.replace(tmp, dump_path)
    else:
        printer(header + _metrics.summary())


_server = None
_server_lock = threading.Lock()


def start_http_server(port: int, host: str = ""):
    """Serve ``/metrics`` (Prometheus) and ``/metrics.json`` on a daemon
    thread; returns the ``HTTPServer`` (``.server_port`` holds the bound
    port — pass ``port=0`` for an ephemeral one). Idempotent per process:
    a second call returns the running server."""
    global _server
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    with _server_lock:
        if _server is not None:
            return _server

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                status = 200
                if path in ("/metrics", "/"):
                    body = to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = to_json().encode()
                    ctype = "application/json"
                elif path in ("/fleet", "/fleet.json"):
                    # lazy import, same reason as /health below; 404 until
                    # a FleetAggregator registers (rank 0 of an aggregated
                    # job)
                    from horovod_tpu.observability import aggregate as _agg

                    try:
                        text = (
                            _agg.fleet_json()
                            if path.endswith(".json")
                            else _agg.fleet_prometheus()
                        )
                    except Exception as e:
                        # the collect hits the rendezvous KV — during a KV
                        # restart the scrape must see a clean 503, not a
                        # dropped socket + handler traceback
                        self.send_error(
                            503, f"fleet aggregation failed: {e}")
                        return
                    if text is None:
                        self.send_error(404, "no fleet aggregator running")
                        return
                    body = text.encode()
                    ctype = (
                        "application/json"
                        if path.endswith(".json")
                        else "text/plain; version=0.0.4; charset=utf-8"
                    )
                elif path == "/health":
                    # lazy import: exporters must stay importable without
                    # dragging the resilience package in at module load
                    from horovod_tpu.resilience import health as _health

                    snap = _health.snapshot()
                    body = json.dumps(snap, indent=1).encode()
                    ctype = "application/json"
                    if snap["value"] >= int(_health.HealthState.DEGRADED):
                        status = 503
                else:
                    self.send_error(404)
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # no per-scrape stderr spam
                pass

        _server = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(
            target=_server.serve_forever,
            name="hvd-metrics-http",
            daemon=True,
        ).start()
        return _server


def stop_http_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None


def maybe_start_http_server():
    """Start the endpoint iff ``HOROVOD_METRICS_PORT`` is set to a valid
    port; returns the server or None. Never raises — observability must not
    take down init (a busy port logs and moves on)."""
    import logging
    import os

    port = os.environ.get("HOROVOD_METRICS_PORT")
    if not port:
        return None
    try:
        return start_http_server(int(port))
    except (ValueError, OSError) as e:
        logging.getLogger("horovod_tpu.observability").warning(
            "could not start metrics endpoint on port %s: %s", port, e
        )
        return None
