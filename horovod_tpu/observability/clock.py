"""Cross-rank clock model: per-rank offset to the rendezvous KV server's
clock, and the skew-corrected merge of per-rank chrome-trace files.

Every rank's host spans are stamped with its OWN ``time.monotonic`` —
monotonic clocks share no origin across processes, so two ranks' views of
one collective land arbitrarily far apart when naively overlaid. The fix is
the classic NTP request/response-midpoint estimate against one shared
reference — the rendezvous KV server's clock (it is already the one process
every rank talks to):

    t0 = local monotonic          (request sent)
    ts = server monotonic         (server read, ridden back in the reply)
    t1 = local monotonic          (response received)
    offset ≈ ts - (t0 + t1) / 2   |error| ≤ (t1 - t0) / 2  (the half-RTT)

:func:`estimate_offset` takes the minimum-RTT sample of N probes (the
tightest bound); :func:`refresh` stores the estimate process-wide, mirrors
it into the ``observability_clock_offset_seconds`` /
``observability_clock_error_seconds`` gauges, and hands the metadata to
:func:`~horovod_tpu.observability.trace.set_clock_info` so every flushed
trace file carries its own correction. The elastic driver re-estimates
after each resize (a new generation may migrate the KV or the host's NTP
may have stepped); on a LAN the error bound is sub-millisecond — document
any correlation tighter than one RTT as unresolvable.

:func:`merge_rank_traces` applies the corrections: each rank file's events
are shifted onto the server timebase (its ``clock_sync`` meta event carries
``epoch_monotonic_ns`` + ``offset_s``), host lanes are renamed
``rank<r>-host``, and the result is one Perfetto load where one
collective's spans — correlated by their ``(step, gen, seq)`` args — align
as a row per rank.

stdlib-only (imported by the launcher-side aggregator and by tools).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Iterable, Optional, Sequence, Tuple

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import trace as _trace

__all__ = [
    "estimate_offset",
    "refresh",
    "refresh_from_kv",
    "offset",
    "error_bound",
    "info",
    "reset",
    "merge_rank_traces",
]

#: probes per estimate; the min-RTT sample wins (NTP's discipline)
DEFAULT_SAMPLES = 5

_lock = threading.Lock()
_offset_s = 0.0
_error_s: Optional[float] = None
_generation = 0
_refreshed_at: Optional[float] = None


def estimate_offset(
    read_server_clock: Callable[[], float], samples: int = DEFAULT_SAMPLES,
) -> Tuple[float, float]:
    """``(offset_seconds, error_bound_seconds)`` between this process's
    ``time.monotonic`` and the clock behind `read_server_clock` (a callable
    returning the server's monotonic seconds). The minimum-RTT probe is
    used: its half-RTT is the tightest achievable bound on the midpoint
    estimate."""
    best: Optional[Tuple[float, float]] = None  # (half_rtt, offset)
    for _ in range(max(1, samples)):
        t0 = time.monotonic()
        ts = float(read_server_clock())
        t1 = time.monotonic()
        half_rtt = (t1 - t0) / 2.0
        off = ts - (t0 + t1) / 2.0
        if best is None or half_rtt < best[0]:
            best = (half_rtt, off)
    return best[1], best[0]


def refresh(
    read_server_clock: Callable[[], float],
    *,
    rank: int = 0,
    generation: Optional[int] = None,
    samples: int = DEFAULT_SAMPLES,
) -> Tuple[float, float]:
    """Estimate and STORE this process's offset (returns ``(offset,
    error_bound)``). Mirrors the estimate into the clock gauges and into
    the trace recorder's ``clock_sync`` metadata so subsequently flushed
    trace files are mergeable."""
    global _offset_s, _error_s, _generation, _refreshed_at
    off, err = estimate_offset(read_server_clock, samples)
    with _lock:
        _offset_s = off
        _error_s = err
        if generation is not None:
            _generation = int(generation)
        _refreshed_at = time.monotonic()
    if _metrics.enabled():
        _metrics.gauge(
            "observability_clock_offset_seconds",
            help="estimated offset of this rank's monotonic clock vs the "
                 "KV server's (request/response midpoint, min-RTT probe)",
        ).set(off)
        _metrics.gauge(
            "observability_clock_error_seconds",
            help="half-RTT error bound on the clock-offset estimate",
        ).set(err)
    _trace.set_clock_info(
        {
            "rank": int(rank),
            "epoch_monotonic_ns": _trace.epoch_ns(),
            "offset_s": off,
            "error_s": err,
            "generation": _generation,
        }
    )
    return off, err


def refresh_from_kv(kv, *, rank: int = 0,
                    generation: Optional[int] = None,
                    samples: int = DEFAULT_SAMPLES) -> Tuple[float, float]:
    """:func:`refresh` against a rendezvous KV server or client — anything
    exposing ``server_clock()`` (both
    :class:`~horovod_tpu.run.rendezvous.KVStoreServer`, in-process, and
    :class:`~horovod_tpu.run.rendezvous.KVStoreClient`, one HTTP round trip
    per probe, do)."""
    return refresh(
        kv.server_clock, rank=rank, generation=generation, samples=samples,
    )


def offset() -> float:
    """The stored offset (0.0 until the first :func:`refresh` — correct for
    the single-process case where local IS the reference clock)."""
    return _offset_s


def error_bound() -> Optional[float]:
    """Half-RTT bound of the stored estimate, or None before any refresh."""
    return _error_s


def info() -> dict:
    """JSON-able view (what the metrics publisher ships with each
    snapshot)."""
    with _lock:
        return {
            "offset_s": _offset_s,
            "error_s": _error_s,
            "generation": _generation,
            "age_s": (
                None if _refreshed_at is None
                else round(time.monotonic() - _refreshed_at, 3)
            ),
        }


def reset() -> None:
    """Back to the unsynchronized state (tests)."""
    global _offset_s, _error_s, _generation, _refreshed_at
    with _lock:
        _offset_s = 0.0
        _error_s = None
        _generation = 0
        _refreshed_at = None


# --------------------------------------------------------------- trace merge


def _load_events(path: str) -> list:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):  # chrome "object format" carries traceEvents
        data = data.get("traceEvents", [])
    return data if isinstance(data, list) else []


def _clock_meta(events: Iterable[dict]) -> Optional[dict]:
    """The LAST clock_sync in the file: ``trace.flush`` appends one per
    flush, so a sidecar reused across shutdown/init cycles (worker
    restart, elastic re-form) carries several — the newest describes the
    timebase of the newest events, which are the ones a fleet merge is
    after. (Events surviving from an earlier run in the same file keep
    that run's timebase and shift imperfectly — a file-wide correction
    cannot serve two epochs; start a fresh HOROVOD_TIMELINE per run when
    that matters.)"""
    meta = None
    for ev in events:
        if ev.get("name") == "clock_sync" and isinstance(
            ev.get("args"), dict
        ):
            meta = ev["args"]
    return meta


def merge_rank_traces(
    paths: Sequence[str],
    out_path: Optional[str] = None,
) -> list:
    """Merge per-rank chrome-trace files into ONE skew-corrected timeline.

    Each file's ``clock_sync`` meta event (written by :func:`refresh` →
    ``trace.flush``) supplies its rank and the mapping of its local
    timebase onto the KV server's clock: absolute server time of an event
    is ``epoch_monotonic_ns/1e9 + ts/1e6 + offset_s``. The earliest file
    origin becomes the merged ts=0; files WITHOUT clock metadata are taken
    at face value (offset 0, rank = position in `paths`) — right for the
    single-process case, increasingly wrong with real skew.

    Host-span lanes (pid ``python-host``) are renamed ``rank<r>-host`` so
    eight ranks' Python rows stay distinguishable; per-rank arrival lanes
    (pid ``rank<r>``) and everything else pass through. Events are sorted
    by corrected timestamp. When `out_path` is given the merged array is
    also written there as valid JSON. Returns the merged event list."""
    per_file = []
    origins = []
    for i, path in enumerate(paths):
        events = _load_events(path)
        meta = _clock_meta(events) or {}
        rank = int(meta.get("rank", i))
        origin_s = (
            float(meta.get("epoch_monotonic_ns", 0)) / 1e9
            + float(meta.get("offset_s", 0.0))
        )
        per_file.append((rank, origin_s, events))
        origins.append(origin_s)
    ref = min(origins) if origins else 0.0
    merged = []
    for rank, origin_s, events in per_file:
        shift_us = (origin_s - ref) * 1e6
        for ev in events:
            ev = dict(ev)
            if ev.get("name") == "clock_sync":
                continue  # consumed; would be misleading post-shift
            if "ts" in ev:
                try:
                    ev["ts"] = round(float(ev["ts"]) + shift_us, 1)
                except (TypeError, ValueError):
                    pass
            if ev.get("pid") == _trace.HOST_PID:
                ev["pid"] = f"rank{rank}-host"
            merged.append(ev)
    merged.sort(key=lambda e: (e.get("ts") or 0.0))
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            # compact: at millions of events, indent would multiply the
            # file size for a file only Perfetto reads
            json.dump(merged, f)
        os.replace(tmp, out_path)
    return merged
