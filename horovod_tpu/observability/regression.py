"""Performance-regression sentinel: rolling baselines + bench trends.

Two consumers of the same idea — "is this metric drifting from its own
history?" — at two timescales (ISSUE 16):

- **In-process** (:class:`Baseline`, :func:`track`): every tracked
  metric keeps an EWMA of its value plus an EWMA of its absolute
  deviation (a MAD proxy), warmup-guarded like the PR-9 numerics spike
  detector — the baseline absorbs only non-drifting samples, so a step
  change is flagged on EVERY sample until it is acknowledged (or
  :func:`forget`), instead of the baseline quietly chasing the
  regression. The training-step wrapper feeds step time, throughput,
  and data-wait through here; a ``drift`` verdict sets
  ``regression_drift{metric=}`` and counts
  ``regression_drift_events{metric=}``.
- **Across runs** (:func:`load_bench`, :func:`trend`): the
  ``BENCH_*.json`` trajectory finally gets a consumer —
  ``tools/hvd_slo.py --trend`` diffs two or more bench files into a
  per-metric trend table with a deterministic regressed/ok verdict per
  row (threshold-fractional, direction inferred from the metric name:
  ``*_per_sec`` / ``*tflops`` / ``*goodput*`` / ``*gbps`` / ``*mfu*``
  are higher-is-better, everything else lower-is-better) and a nonzero
  exit on regression.

Knobs: ``HOROVOD_SLO_DRIFT_ALPHA`` (EWMA smoothing, default 0.2),
``HOROVOD_SLO_DRIFT_WARMUP`` (samples absorbed before verdicts,
default 20), ``HOROVOD_SLO_DRIFT_FACTOR`` (deviation multiple that
counts as drift, default 8.0; a relative floor of 25% of the baseline
keeps near-constant series from flagging on timer jitter).

stdlib-only.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from horovod_tpu.observability import metrics as _metrics

__all__ = [
    "DRIFT_ALPHA_ENV",
    "DRIFT_WARMUP_ENV",
    "DRIFT_FACTOR_ENV",
    "Baseline",
    "track",
    "verdicts",
    "forget",
    "reset",
    "load_bench",
    "higher_is_better",
    "trend",
]

DRIFT_ALPHA_ENV = "HOROVOD_SLO_DRIFT_ALPHA"
DRIFT_WARMUP_ENV = "HOROVOD_SLO_DRIFT_WARMUP"
DRIFT_FACTOR_ENV = "HOROVOD_SLO_DRIFT_FACTOR"

#: drift needs the deviation to also exceed this fraction of the
#: baseline — an all-but-constant series (MAD -> 0) must not flag on
#: scheduler jitter
_REL_FLOOR = 0.25


class Baseline:
    """EWMA + MAD rolling baseline with warmup-guarded drift verdicts.

    The PR-9 numerics-EWMA shape: during warmup every sample absorbs
    and the verdict is ``"warmup"``; after warmup a sample whose
    absolute deviation exceeds ``factor * max(MAD, rel_floor *
    |baseline|)`` is ``"drift"`` and is NOT absorbed (the baseline
    remembers what normal looked like); everything else absorbs and is
    ``"ok"``."""

    def __init__(self, *, alpha: Optional[float] = None,
                 warmup: Optional[int] = None,
                 factor: Optional[float] = None,
                 rel_floor: float = _REL_FLOOR):
        self.alpha = float(
            alpha if alpha is not None
            else os.environ.get(DRIFT_ALPHA_ENV, "0.2"))
        self.warmup = int(
            warmup if warmup is not None
            else os.environ.get(DRIFT_WARMUP_ENV, "20"))
        self.factor = float(
            factor if factor is not None
            else os.environ.get(DRIFT_FACTOR_ENV, "8.0"))
        self.rel_floor = float(rel_floor)
        self.ewma: Optional[float] = None
        self.mad = 0.0
        self.n = 0          # absorbed (good) samples only
        self.streak = 0     # consecutive drift verdicts

    def _absorb(self, value: float, dev: float) -> None:
        if self.ewma is None:
            self.ewma = value
        else:
            self.ewma += self.alpha * (value - self.ewma)
        self.mad += self.alpha * (dev - self.mad)
        self.n += 1
        self.streak = 0

    def update(self, value: float) -> dict:
        value = float(value)
        dev = 0.0 if self.ewma is None else abs(value - self.ewma)
        if self.n < self.warmup:
            self._absorb(value, dev)
            state = "warmup"
        else:
            spread = max(self.mad,
                         self.rel_floor * abs(self.ewma or 0.0))
            if dev > self.factor * spread:
                self.streak += 1
                state = "drift"
            else:
                self._absorb(value, dev)
                state = "ok"
        return {
            "state": state,
            "value": value,
            "ewma": self.ewma,
            "mad": self.mad,
            "deviation": dev,
            "streak": self.streak,
        }


_lock = threading.Lock()
_baselines: Dict[str, Baseline] = {}
_last: Dict[str, dict] = {}


def track(name: str, value: float, **baseline_kwargs) -> dict:
    """Feed one sample of `name` through its rolling baseline and
    publish the verdict (``regression_drift{metric=}`` gauge;
    ``regression_drift_events{metric=}`` counts drifting samples)."""
    with _lock:
        b = _baselines.get(name)
        if b is None:
            b = Baseline(**baseline_kwargs)
            _baselines[name] = b
        v = b.update(value)
        _last[name] = v
    if _metrics.enabled():
        _metrics.gauge(
            "regression_drift",
            help="1 while the metric's latest sample drifts from its "
                 "rolling EWMA+MAD baseline, else 0",
            metric=name,
        ).set(1.0 if v["state"] == "drift" else 0.0)
        if v["state"] == "drift":
            _metrics.counter(
                "regression_drift_events",
                help="samples that drifted from their rolling baseline",
                metric=name,
            ).inc()
    return v


def verdicts() -> Dict[str, dict]:
    """Latest verdict per tracked metric."""
    with _lock:
        return dict(_last)


def forget(name: str) -> None:
    """Drop one metric's baseline (re-warms on next sample) — the
    acknowledge-a-regime-change path."""
    with _lock:
        _baselines.pop(name, None)
        _last.pop(name, None)


def reset() -> None:
    """Drop every baseline (tests)."""
    with _lock:
        _baselines.clear()
        _last.clear()


# ------------------------------------------------------- bench trends


def load_bench(path: str) -> Dict[str, float]:
    """Parse one ``BENCH_*.json`` / ``--serving-ab``-style file into its
    numeric fields. Tolerant of JSON-lines (every parseable line's
    numeric fields merge, later lines win) — the bench emits one flat
    JSON object per line."""
    out: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if not isinstance(obj, dict):
                continue
            for k, v in obj.items():
                if isinstance(v, bool):
                    continue
                if isinstance(v, (int, float)):
                    out[str(k)] = float(v)
    return out


_HIGHER_BETTER_MARKS = (
    "per_sec", "per_second", "tflops", "gbps", "goodput", "mfu",
    "tokens_per", "examples_per", "images_per", "throughput",
)


def higher_is_better(metric: str) -> bool:
    m = metric.lower()
    return any(mark in m for mark in _HIGHER_BETTER_MARKS)


def trend(series: List[Dict[str, float]], *,
          threshold: float = 0.05) -> dict:
    """Diff >= 2 bench snapshots (oldest first) into a per-metric trend
    table. The baseline for each metric is the EWMA of every snapshot
    but the last (alpha 0.5, seeded on the first value — deterministic);
    the last snapshot regresses when it is worse than that baseline by
    more than `threshold` (fractional), direction per
    :func:`higher_is_better`. Metrics missing from the last snapshot
    are skipped; metrics new in it have no baseline and cannot regress.
    """
    if len(series) < 2:
        raise ValueError(
            f"trend needs >= 2 bench snapshots, got {len(series)}")
    rows = []
    regressed = []
    last = series[-1]
    for metric in sorted(last):
        values = [s[metric] for s in series if metric in s]
        if len(values) < 2:
            continue
        base = values[0]
        for v in values[1:-1]:
            base += 0.5 * (v - base)
        cur = values[-1]
        if base == 0.0:
            delta = 0.0
        else:
            delta = (cur - base) / abs(base)
        better_up = higher_is_better(metric)
        bad = (-delta if better_up else delta) > threshold
        rows.append({
            "metric": metric,
            "values": values,
            "baseline": base,
            "last": cur,
            "delta_frac": delta,
            "direction": "higher_is_better" if better_up
            else "lower_is_better",
            "regressed": bad,
        })
        if bad:
            regressed.append(metric)
    return {"rows": rows, "regressed": regressed,
            "threshold": threshold}
