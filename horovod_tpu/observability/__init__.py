"""Unified metrics & host tracing for horovod_tpu.

Three stdlib-only modules (importing them must never touch JAX or
initialize a device backend — pinned by ``tests/test_metrics.py``):

- :mod:`~horovod_tpu.observability.metrics` — process-local registry of
  counters, gauges, and fixed-bucket histograms with labeled children.
  The instrumented layers (``core.py`` cycle callback, the eager ops in
  ``ops/collective.py``, the training-step wrappers) feed it; ``bench.py``
  and user code read it via ``hvd.metrics.snapshot()`` /
  ``hvd.metrics.summary()``.
- :mod:`~horovod_tpu.observability.exporters` — Prometheus text
  exposition + JSON snapshot, and the opt-in rank-0 HTTP endpoint
  (``HOROVOD_METRICS_PORT``).
- :mod:`~horovod_tpu.observability.trace` — host-side chrome-trace span
  recorder that merges Python-layer phases (enqueue, plan receipt, eager
  dispatch) into the SAME ``HOROVOD_TIMELINE`` file the native core
  writes, so one Perfetto load shows controller + host activity (add the
  XLA device trace from :mod:`horovod_tpu.profiler` for the full picture).

See ``docs/observability.md`` for the metrics catalog and workflows.
"""

from horovod_tpu.observability import exporters, metrics, trace  # noqa: F401
