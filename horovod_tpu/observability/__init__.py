"""Unified metrics & host tracing for horovod_tpu.

Ten stdlib-only modules (importing them must never initialize a device
backend — pinned by ``tests/test_metrics.py``):

- :mod:`~horovod_tpu.observability.metrics` — process-local registry of
  counters, gauges, and fixed-bucket histograms with labeled children.
  The instrumented layers (``core.py`` cycle callback, the eager ops in
  ``ops/collective.py``, the training-step wrappers) feed it; ``bench.py``
  and user code read it via ``hvd.metrics.snapshot()`` /
  ``hvd.metrics.summary()``.
- :mod:`~horovod_tpu.observability.exporters` — Prometheus text
  exposition + JSON snapshot, and the opt-in rank-0 HTTP endpoint
  (``HOROVOD_METRICS_PORT``) — serving the fleet view at ``/fleet`` /
  ``/fleet.json`` once an aggregator registers.
- :mod:`~horovod_tpu.observability.trace` — host-side chrome-trace span
  recorder (capped ring, ``HOROVOD_TRACE_MAX_SPANS``) that merges
  Python-layer phases into the SAME ``HOROVOD_TIMELINE`` file the native
  core writes; ranks != 0 flush per-rank sidecars for the fleet merge.
- :mod:`~horovod_tpu.observability.clock` — per-rank clock-offset
  estimation against the rendezvous KV server (request/response midpoint)
  and the skew-corrected merge of per-rank trace files.
- :mod:`~horovod_tpu.observability.straggler` — ``(step, generation,
  seq)`` correlation keys on every eager collective, per-rank arrival
  recording, and arrival-spread attribution feeding ``straggler_rank`` +
  the resilience health machine.
- :mod:`~horovod_tpu.observability.aggregate` — the cross-rank metric
  plane: per-rank snapshot publication to the KV (TTL'd) and the rank-0
  fleet aggregator (min/mean/max/p99 across ranks, rank-labeled raw
  series, dead ranks surfaced).
- :mod:`~horovod_tpu.observability.flight` — the black-box flight
  recorder: an always-on bounded ring of structured events (collective
  begin/end with ``(step, gen, seq)``, step boundaries, health
  transitions, chaos injections, elastic epochs, serving admissions)
  checkpointed to a crash-durable per-rank sidecar
  (``HOROVOD_FLIGHT_DIR``), plus the ``HOROVOD_HANG_TIMEOUT`` watchdog
  whose cross-rank diagnosis names the hung rank and collective;
  ``tools/hvd_blackbox.py`` replays the same analysis offline.
- :mod:`~horovod_tpu.observability.slo` — declarative SLO objectives
  (``HOROVOD_SLO=ttft_p99<0.5s,...``) with deterministic multi-window
  burn-rate math counted in steps/requests; a burning objective feeds
  the health machine (``record_slo_burn``) and the
  ``slo_burn_rate{objective=}`` / ``slo_budget_remaining{objective=}``
  gauges, and the rollout controller's canary gate judges through the
  same evaluator.
- :mod:`~horovod_tpu.observability.reqtrace` — per-request span
  lifecycle for the serving engine (queue wait, admission, prefill
  chunks, TTFT, TPOT, completion) landing in ``req:<id>`` chrome-trace
  lanes, rid-correlated flight events, and the
  ``reqtrace_*_seconds{arm,outcome,generation}`` histograms + bounded
  per-arm windows the rollout/SLO gates read.
- :mod:`~horovod_tpu.observability.regression` — the
  performance-regression sentinel: warmup-guarded EWMA+MAD rolling
  baselines producing deterministic drift verdicts on step time /
  throughput / data-wait in-process, plus the ``BENCH_*.json`` trend
  differ behind ``tools/hvd_slo.py --trend``.

See ``docs/observability.md`` for the metrics catalog and workflows,
``tools/hvd_top.py`` for the live terminal view, and
``tools/hvd_slo.py`` for the SLO status / bench-trend CLI.
"""

from horovod_tpu.observability import (  # noqa: F401
    exporters,
    metrics,
    trace,
    clock,
    straggler,
    aggregate,
    flight,
    slo,
    regression,
    reqtrace,
)
