"""Per-request span lifecycle for the serving engine (ISSUE 16).

The serving stack's request-level observability: every request the
continuous-batching engine touches is followed queue → admission →
chunked prefill → first token → per-token decode → completion (or
rejection, or canary relabel), and each transition lands in three places
at once:

- **Chrome-trace lanes** — the existing ``HOROVOD_TIMELINE`` host ring
  gains one ``req:<rid>`` pid lane per request, with a ``queue_wait``
  span, an ``admit`` instant (slot + reserved pages), one span per
  prefill chunk iteration, a ``first_token`` instant (TTFT), one span
  per decoded token (TPOT cadence), and a whole-request span at
  completion.
- **Flight-recorder events** —``req_begin`` / ``req_end`` /
  ``req_relabel`` events on the ``serve`` kind carry the SAME request
  id, so ``tools/hvd_blackbox.py`` can group a dead job's sidecars per
  request and say which in-flight requests a hang stranded.
- **Histograms** — TTFT / TPOT / queue-wait / e2e land in
  ``reqtrace_*_seconds`` families labeled ``{arm,outcome,generation}``,
  subsuming the scheduler's old hand-rolled
  ``serving_request_latency_seconds`` observation (kept as an alias so
  dashboards survive).

The same completions feed **bounded per-arm windows** (seqno-tagged, so
readers take a mark and ask "what completed since") that
:class:`~horovod_tpu.serving.rollout.GenerationRollout` reads for its
canary gate and :mod:`~horovod_tpu.observability.slo` evaluates
objectives against — one observation path instead of the double-booked
rollout-window / scheduler-histogram pair this replaces.

``HOROVOD_REQTRACE=0`` disables the trace/flight/histogram *emission*;
the windowed accounting always runs (the rollout gate and SLO evaluator
depend on it and it is a few deque appends per request).
``HOROVOD_REQTRACE_WINDOW`` bounds the per-arm windows (default 256
completions).

ISSUE 17 extends the lifecycle for the fleet tier: requests carry an
optional ``replica`` label (stamped on flight events and trace args so
a multi-replica flight record attributes each span to the engine that
served it), completions fan out to registered *observers*
(:func:`add_completion_observer` — the fleet router builds its
per-replica gate windows this way instead of double-booking the
accounting), and :func:`recent_tpot` exposes the windowed decode-gap
median for deterministic backpressure hints. A completion whose error
starts with ``"cancelled"`` (a hedge loser withdrawn by the router) is
excluded from the arm windows and the error-rate SLO — it was never a
served outcome.

stdlib-only, like the rest of the observability package. Hooks are
called by :mod:`horovod_tpu.serving.scheduler` /
:mod:`horovod_tpu.serving.engine` outside their locks; all module state
here is guarded by one lock.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from horovod_tpu.observability import flight as _flight
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import slo as _slo
from horovod_tpu.observability import trace as _trace

logger = logging.getLogger(__name__)

__all__ = [
    "REQTRACE_ENV",
    "WINDOW_ENV",
    "enabled",
    "window_size",
    "reset",
    "on_enqueue",
    "on_reject",
    "on_admit",
    "on_prefill_chunk",
    "on_prefix_hit",
    "on_spec_verify",
    "on_first_token",
    "on_token",
    "on_finish",
    "on_relabel",
    "arm_mark",
    "arm_window",
    "quantile",
    "live_requests",
    "add_completion_observer",
    "remove_completion_observer",
    "recent_tpot",
]

REQTRACE_ENV = "HOROVOD_REQTRACE"
WINDOW_ENV = "HOROVOD_REQTRACE_WINDOW"

_lock = threading.Lock()
_enabled_cache: Optional[bool] = None
_window_cache: Optional[int] = None


class _Rec:
    """Live state for one in-flight request (keyed by ``id(req)`` — rids
    are caller-chosen and need not be unique across retries)."""

    __slots__ = ("rid", "arm", "replica", "t_enqueue", "t_admit",
                 "t_first", "t_last", "generation", "tokens",
                 "tpot_sum", "cached_tokens", "spec_proposed",
                 "spec_accepted")

    def __init__(self, rid, arm: str, t_enqueue: float,
                 replica: str = ""):
        self.rid = rid
        self.arm = arm
        self.replica = replica
        self.t_enqueue = t_enqueue
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        self.generation: int = -1
        self.tokens = 0
        self.tpot_sum = 0.0
        #: prompt tokens aliased from the prefix cache (skipped prefill
        #: — the TTFT attribution for a cache hit)
        self.cached_tokens = 0
        #: draft tokens proposed / accepted for this request (the TPOT
        #: attribution for speculative decode)
        self.spec_proposed = 0
        self.spec_accepted = 0


class _ArmSeries:
    """Bounded completion window for one user-facing arm. Entries are
    seqno-tagged so concurrent readers (rollout gate, SLO evaluator,
    p50/p99 gauges) can each keep their own mark."""

    __slots__ = ("seq", "done", "tpot")

    def __init__(self, window: int):
        self.seq = 0
        # (seqno, generation, error, e2e, ttft, tpot_mean)
        self.done: deque = deque(maxlen=window)
        # token-level inter-token gaps, for the p50/p99 gauges
        self.tpot: deque = deque(maxlen=window)


_live: Dict[int, _Rec] = {}
_arms: Dict[str, _ArmSeries] = {}
# completion observers (fleet router): fn(req, summary_dict), called
# outside the module lock on every on_finish
_observers: List = []


def _replica_of(req) -> str:
    return str(getattr(req, "replica", "") or "")


def add_completion_observer(fn) -> None:
    """Register `fn(req, summary)` to run on every completion.
    `summary` carries rid / replica / arm / generation / error /
    cancelled / e2e / ttft / tpot_mean; `req` is the scheduler-level
    request object (identity lets the fleet router match its own
    copies). Observers run outside the reqtrace lock; exceptions are
    swallowed so a broken observer cannot wedge the engine."""
    with _lock:
        if fn not in _observers:
            _observers.append(fn)


def remove_completion_observer(fn) -> None:
    """Unregister a completion observer (no-op when unknown)."""
    with _lock:
        if fn in _observers:
            _observers.remove(fn)


def enabled() -> bool:
    """Emission switch (``HOROVOD_REQTRACE``, default on). Gates the
    trace-lane / flight-event / histogram output, NOT the windowed
    accounting."""
    global _enabled_cache
    with _lock:
        if _enabled_cache is None:
            _enabled_cache = os.environ.get(REQTRACE_ENV, "1") != "0"
        return _enabled_cache


def window_size() -> int:
    """Per-arm completion-window bound (``HOROVOD_REQTRACE_WINDOW``)."""
    global _window_cache
    with _lock:
        if _window_cache is None:
            _window_cache = max(
                1, int(os.environ.get(WINDOW_ENV, "256")))
        return _window_cache


def reset() -> None:
    """Drop live records, windows, and cached env (tests)."""
    global _enabled_cache, _window_cache
    with _lock:
        _live.clear()
        _arms.clear()
        _observers.clear()
        _enabled_cache = None
        _window_cache = None


def _series(arm: str) -> _ArmSeries:
    # caller holds _lock
    s = _arms.get(arm)
    if s is None:
        s = _ArmSeries(window_size_unlocked())
        _arms[arm] = s
    return s


def window_size_unlocked() -> int:
    global _window_cache
    if _window_cache is None:
        _window_cache = max(1, int(os.environ.get(WINDOW_ENV, "256")))
    return _window_cache


def quantile(values: List[float], q: float) -> Optional[float]:
    """Deterministic nearest-rank quantile (no interpolation — two
    processes computing p99 over the same window agree bit-for-bit)."""
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, math.ceil(q * len(vs)) - 1))
    return vs[idx]


def live_requests() -> List[dict]:
    """Snapshot of in-flight request records (diagnostics / tests)."""
    with _lock:
        return [
            {"rid": r.rid, "arm": r.arm, "tokens": r.tokens,
             "admitted": r.t_admit is not None}
            for r in _live.values()
        ]


# ------------------------------------------------------------ lifecycle


def on_enqueue(req) -> None:
    """A request entered the queue (scheduler accepted it)."""
    replica = _replica_of(req)
    rec = _Rec(req.rid, req.arm, req.submitted_at, replica)
    with _lock:
        _live[id(req)] = rec
    if not enabled():
        return
    _flight.record("serve", what="req_begin", rid=str(req.rid),
                   arm=req.arm,
                   **({"replica": replica} if replica else {}))
    if _trace.enabled():
        _trace.add_raw({
            "ph": "i", "s": "t", "pid": f"req:{req.rid}",
            "tid": "lifecycle", "name": "enqueue",
            "ts": round(_trace.rel_us(req.submitted_at), 1),
            "args": {"arm": req.arm,
                     **({"replica": replica} if replica else {})},
        })


def on_reject(req, reason: str) -> None:
    """Admission control refused the request (queue full / too long)."""
    with _lock:
        _live.pop(id(req), None)
    now = time.monotonic()
    lat = now - req.submitted_at
    if _metrics.enabled():
        _metrics.histogram(
            "reqtrace_e2e_seconds",
            help="submit-to-finish wall time per request "
                 "(queue wait included)",
            arm=req.arm, outcome="rejected", generation="-1",
        ).observe(lat)
    _slo.observe("error_rate", 1.0)
    if not enabled():
        return
    replica = _replica_of(req)
    _flight.record("serve", what="req_end", rid=str(req.rid),
                   arm=req.arm, outcome="rejected", reason=reason,
                   **({"replica": replica} if replica else {}))
    if _trace.enabled():
        _trace.add_raw({
            "ph": "X", "pid": f"req:{req.rid}", "tid": "lifecycle",
            "name": "rejected",
            "ts": round(_trace.rel_us(req.submitted_at), 1),
            "dur": round(lat * 1e6, 1),
            "args": {"arm": req.arm, "reason": reason},
        })


def on_admit(seq) -> None:
    """A queued request took a batch slot + full page reservation."""
    req = seq.req
    now = time.monotonic()
    with _lock:
        rec = _live.get(id(req))
        if rec is None:
            rec = _Rec(req.rid, req.arm, req.submitted_at)
            _live[id(req)] = rec
        rec.t_admit = now
        rec.arm = req.arm
    wait = now - req.submitted_at
    if _metrics.enabled():
        _metrics.histogram(
            "reqtrace_queue_wait_seconds",
            help="enqueue-to-admission wait per request",
            arm=req.arm,
        ).observe(wait)
    _slo.observe("queue_wait", wait)
    if not enabled() or not _trace.enabled():
        return
    pid = f"req:{req.rid}"
    _trace.add_raw({
        "ph": "X", "pid": pid, "tid": "lifecycle", "name": "queue_wait",
        "ts": round(_trace.rel_us(req.submitted_at), 1),
        "dur": round(wait * 1e6, 1),
        "args": {"arm": req.arm},
    })
    _trace.add_raw({
        "ph": "i", "s": "t", "pid": pid, "tid": "lifecycle",
        "name": "admit", "ts": round(_trace.rel_us(now), 1),
        "args": {"slot": seq.slot, "pages": len(seq.pages),
                 "arm": seq.arm},
    })


def on_prefill_chunk(seq, ntokens: int, t0: float,
                     generation: int) -> None:
    """One chunked-prefill iteration wrote `ntokens` of this sequence's
    prompt (``t0`` = pass start, ``time.monotonic()``)."""
    req = seq.req
    with _lock:
        rec = _live.get(id(req))
        if rec is not None:
            rec.generation = int(generation)
    if not enabled() or not _trace.enabled():
        return
    _trace.add_raw({
        "ph": "X", "pid": f"req:{req.rid}", "tid": "engine",
        "name": f"prefill[{ntokens}]",
        "ts": round(_trace.rel_us(t0), 1),
        "dur": round((time.monotonic() - t0) * 1e6, 1),
        "args": {"arm": seq.arm, "generation": int(generation)},
    })


def on_prefix_hit(seq, ntokens: int) -> None:
    """Admission aliased `ntokens` cached prompt tokens for this
    request — those prefill chunks are skipped entirely, which is the
    TTFT story a cache hit tells on the trace lane."""
    req = seq.req
    now = time.monotonic()
    with _lock:
        rec = _live.get(id(req))
        if rec is not None:
            rec.cached_tokens = int(ntokens)
    if not enabled() or not _trace.enabled():
        return
    _trace.add_raw({
        "ph": "i", "s": "t", "pid": f"req:{req.rid}", "tid": "engine",
        "name": "prefix_hit", "ts": round(_trace.rel_us(now), 1),
        "args": {"cached_tokens": int(ntokens), "arm": seq.arm},
    })


def on_spec_verify(seq, proposed: int, accepted: int,
                   generation: int) -> None:
    """One speculative iteration verified for this request: `proposed`
    draft tokens, `accepted` of them kept (plus the bonus token the
    verify forward emits regardless). The per-iteration TPOT gaps the
    :func:`on_token` cadence records around this event are the
    speculative attribution: one verify wall-clock amortized over
    ``accepted + 1`` tokens."""
    req = seq.req
    now = time.monotonic()
    with _lock:
        rec = _live.get(id(req))
        if rec is not None:
            rec.spec_proposed += int(proposed)
            rec.spec_accepted += int(accepted)
            rec.generation = int(generation)
    if not enabled() or not _trace.enabled():
        return
    _trace.add_raw({
        "ph": "i", "s": "t", "pid": f"req:{req.rid}", "tid": "engine",
        "name": "spec_verify", "ts": round(_trace.rel_us(now), 1),
        "args": {"proposed": int(proposed), "accepted": int(accepted),
                 "arm": seq.arm, "generation": int(generation)},
    })


def on_first_token(seq, generation: int) -> None:
    """The request's first token sampled — TTFT closes here."""
    req = seq.req
    now = time.monotonic()
    ttft = now - req.submitted_at
    with _lock:
        rec = _live.get(id(req))
        if rec is not None:
            rec.t_first = now
            rec.t_last = now
            rec.tokens = 1
            rec.generation = int(generation)
    if _metrics.enabled():
        _metrics.histogram(
            "reqtrace_ttft_seconds",
            help="submit-to-first-token wall time per request (TTFT)",
            arm=req.arm, generation=str(int(generation)),
        ).observe(ttft)
    _slo.observe("ttft", ttft)
    if not enabled() or not _trace.enabled():
        return
    _trace.add_raw({
        "ph": "i", "s": "t", "pid": f"req:{req.rid}", "tid": "engine",
        "name": "first_token", "ts": round(_trace.rel_us(now), 1),
        "args": {"ttft_ms": round(ttft * 1e3, 3), "arm": seq.arm,
                 "generation": int(generation)},
    })


def on_token(seq, generation: int) -> None:
    """One decode token sampled — the TPOT cadence."""
    req = seq.req
    now = time.monotonic()
    gap = None
    with _lock:
        rec = _live.get(id(req))
        if rec is not None:
            if rec.t_last is not None:
                gap = now - rec.t_last
                rec.tpot_sum += gap
            rec.t_last = now
            rec.tokens += 1
            rec.generation = int(generation)
            if gap is not None:
                _series(req.arm).tpot.append(gap)
    if gap is None:
        return
    if _metrics.enabled():
        _metrics.histogram(
            "reqtrace_tpot_seconds",
            help="inter-token decode gap per generated token (TPOT)",
            arm=req.arm, generation=str(int(generation)),
        ).observe(gap)
    _slo.observe("tpot", gap)
    if not enabled() or not _trace.enabled():
        return
    _trace.add_raw({
        "ph": "X", "pid": f"req:{req.rid}", "tid": "engine",
        "name": "decode_token",
        "ts": round(_trace.rel_us(now - gap), 1),
        "dur": round(gap * 1e6, 1),
        "args": {"arm": seq.arm},
    })


def on_finish(seq, *, error: Optional[str] = None) -> None:
    """A sequence retired at an iteration boundary — the one completion
    observation path (the scheduler's old
    ``serving_request_latency_seconds`` lives on as an alias of the e2e
    series recorded here)."""
    req = seq.req
    cancelled = bool(error) and str(error).startswith("cancelled")
    outcome = "cancelled" if cancelled \
        else ("error" if error else "ok")
    lat = req.latency_seconds()
    with _lock:
        rec = _live.pop(id(req), None)
        generation = rec.generation if rec is not None else -1
        ttft = (rec.t_first - rec.t_enqueue) \
            if rec is not None and rec.t_first is not None else None
        tpot_mean = None
        if rec is not None and rec.tokens > 1:
            tpot_mean = rec.tpot_sum / (rec.tokens - 1)
        s = _series(req.arm)
        s.seq += 1
        if lat is not None and not cancelled:
            s.done.append((s.seq, generation, bool(error), lat, ttft,
                           tpot_mean))
        ttft_vals = [e[4] for e in s.done if e[4] is not None]
        tpot_vals = list(s.tpot)
        observers = list(_observers)
    if observers:
        summary = {
            "rid": req.rid,
            "replica": rec.replica if rec is not None
            else _replica_of(req),
            "arm": req.arm, "generation": generation,
            "error": error, "cancelled": cancelled,
            "e2e": lat, "ttft": ttft, "tpot_mean": tpot_mean,
            # hot-path attribution: how much of this request's latency
            # the cache/speculation machinery explains
            "cached_tokens": rec.cached_tokens if rec is not None else 0,
            "spec_proposed": rec.spec_proposed if rec is not None else 0,
            "spec_accepted": rec.spec_accepted if rec is not None else 0,
        }
        for fn in observers:
            try:
                fn(req, summary)
            except Exception as e:  # noqa: BLE001 - observers best-effort
                logger.debug("completion observer %r failed: %s", fn, e)
    if _metrics.enabled() and lat is not None:
        _metrics.histogram(
            "reqtrace_e2e_seconds",
            help="submit-to-finish wall time per request "
                 "(queue wait included)",
            arm=req.arm, outcome=outcome,
            generation=str(int(generation)),
        ).observe(lat)
        # alias: the pre-reqtrace scheduler observation, kept so
        # existing dashboards / the A-B bench keep reading
        _metrics.histogram(
            "serving_request_latency_seconds",
            help="submit-to-finish wall time per request",
            arm=req.arm,
        ).observe(lat)
        for q, qname in ((0.5, "p50"), (0.99, "p99")):
            tv = quantile(ttft_vals, q)
            if tv is not None:
                _metrics.gauge(
                    f"reqtrace_ttft_{qname}",
                    help="windowed TTFT quantile per arm (seconds)",
                    arm=req.arm,
                ).set(tv)
            pv = quantile(tpot_vals, q)
            if pv is not None:
                _metrics.gauge(
                    f"reqtrace_tpot_{qname}",
                    help="windowed TPOT quantile per arm (seconds)",
                    arm=req.arm,
                ).set(pv)
    if lat is not None and not cancelled:
        _slo.observe("e2e", lat)
    if not cancelled:
        _slo.observe("error_rate", 1.0 if error else 0.0)
    if not enabled():
        return
    replica = rec.replica if rec is not None else _replica_of(req)
    _flight.record("serve", what="req_end", rid=str(req.rid),
                   arm=req.arm, outcome=outcome,
                   **({"replica": replica} if replica else {}))
    if _trace.enabled() and lat is not None:
        _trace.add_raw({
            "ph": "X", "pid": f"req:{req.rid}", "tid": "lifecycle",
            "name": f"request:{outcome}",
            "ts": round(_trace.rel_us(req.submitted_at), 1),
            "dur": round(lat * 1e6, 1),
            "args": {"arm": req.arm, "generation": int(generation),
                     "tokens": rec.tokens if rec is not None else 0,
                     **({"error": error} if error else {})},
        })


def on_relabel(req, src: str, dst: str) -> None:
    """A queued request moved arms (rollback re-route / promotion)."""
    with _lock:
        rec = _live.get(id(req))
        if rec is not None:
            rec.arm = dst
    if not enabled():
        return
    _flight.record("serve", what="req_relabel", rid=str(req.rid),
                   src=src, dst=dst)
    if _trace.enabled():
        _trace.add_raw({
            "ph": "i", "s": "t", "pid": f"req:{req.rid}",
            "tid": "lifecycle", "name": f"relabel:{src}->{dst}",
            "ts": round(_trace.rel_us(time.monotonic()), 1),
            "args": {"src": src, "dst": dst},
        })


# -------------------------------------------------------------- readers


def arm_mark(arm: str) -> int:
    """Current completion seqno for `arm` — take one, then ask
    :func:`arm_window` what completed *since* (the rollout gate's
    fresh-window idiom, replacing its hand-rolled accumulator)."""
    with _lock:
        s = _arms.get(arm)
        return 0 if s is None else s.seq


def arm_window(arm: str, since: int = 0,
               generation: Optional[int] = None) -> Dict[str, object]:
    """Completions on `arm` with seqno > `since` (and, when `generation`
    is given, decoded under exactly that weight generation — a leftover
    from a rolled-back canary never pollutes a later gate window)."""
    with _lock:
        s = _arms.get(arm)
        entries = [] if s is None else [
            e for e in s.done
            if e[0] > since and (generation is None
                                 or e[1] == int(generation))
        ]
    ttft = [e[4] for e in entries if e[4] is not None]
    tpot = [e[5] for e in entries if e[5] is not None]
    e2e = [e[3] for e in entries]
    return {
        "done": len(entries),
        "errors": sum(1 for e in entries if e[2]),
        "latency_sum": float(sum(e2e)),
        "e2e": e2e,
        "ttft": ttft,
        "tpot": tpot,
    }


def recent_tpot(default: Optional[float] = None) -> Optional[float]:
    """Windowed median inter-token decode gap across every arm, or
    `default` when nothing has decoded yet. Nearest-rank over bounded
    deques, so the backpressure hint derived from it
    (:meth:`~horovod_tpu.serving.scheduler.Scheduler.backpressure_hint`)
    is deterministic for a given completion history."""
    with _lock:
        vals = [g for s in _arms.values() for g in s.tpot]
    if not vals:
        return default
    return quantile(vals, 0.5)
