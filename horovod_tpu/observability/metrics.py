"""Process-local metrics registry: counters, gauges, fixed-bucket histograms.

The reference Horovod exposes no queryable metrics at all — cycle times,
fusion efficiency, and cache behavior are visible only through the chrome
Timeline or one-off logging. This registry is the rebuild's first-class
answer: instrumented layers call ``counter("allreduce_bytes").inc(n)`` and
anything (tests, ``bench.py``, the ``MetricsCallback``, the Prometheus
endpoint) reads a consistent snapshot.

Design constraints, in order:

1. **stdlib only** — importing this module must never import JAX or touch a
   device backend (it is imported from hot paths that also run during
   test collection under ``JAX_PLATFORMS=cpu``).
2. **near-zero cost when disabled** — ``HOROVOD_METRICS_ENABLED=0`` (or
   :func:`set_enabled`\\(False)) makes every accessor return a shared no-op
   whose ``inc``/``set``/``observe`` do nothing; the per-event cost is one
   global bool check.
3. **lock-safe** — one registry lock guards family/child creation; each
   child serializes its own updates, so concurrent ``inc`` from the core's
   cycle thread, the bucket flusher, and user threads never lose counts.

Usage::

    from horovod_tpu.observability import metrics
    metrics.counter("allreduce_count").inc()
    metrics.counter("allreduce_bytes", rank=0).inc(4096)
    metrics.histogram("core_cycle_latency_seconds").observe(0.003)
    snap = metrics.snapshot()
    print(metrics.summary())
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "summary",
    "value",
    "reset",
    "enabled",
    "set_enabled",
    "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_SIZE_BUCKETS",
]

#: seconds — spans 100µs cycle callbacks to multi-second stalls
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: dimensionless sizes/counts — tensors per fused plan, bytes per op
DEFAULT_SIZE_BUCKETS = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 1024, 4096, 16384, 65536,
    262144, 1048576, 16777216, 268435456,
)


def _env_enabled() -> bool:
    return os.environ.get(
        "HOROVOD_METRICS_ENABLED", "1"
    ).lower() not in ("0", "false", "off")


_enabled = _env_enabled()


def enabled() -> bool:
    """Global metrics switch (``HOROVOD_METRICS_ENABLED``, default on)."""
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip the global switch at runtime (tests; per-job opt-out). Metrics
    recorded before disabling remain in the registry."""
    global _enabled
    _enabled = bool(on)


class Counter:
    """Monotonically increasing value (one labeled child)."""

    __slots__ = ("_lock", "_value")

    kind = "counter"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def _sample(self):
        return self._value


class Gauge:
    """Set-to-current value (one labeled child)."""

    __slots__ = ("_lock", "_value")

    kind = "gauge"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def _sample(self):
        return self._value


class Histogram:
    """Fixed-bucket histogram (one labeled child). Buckets are cumulative
    upper bounds, Prometheus-style; an implicit ``+Inf`` bucket catches the
    tail. Bucket bounds are fixed at family creation so children and
    snapshots always agree."""

    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count")

    kind = "histogram"

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(buckets)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            return  # a NaN observation would poison sum forever
        i = 0
        for bound in self.buckets:
            if v <= bound:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _sample(self):
        with self._lock:
            cumulative, out = 0, {}
            for bound, c in zip(self.buckets, self._counts):
                cumulative += c
                out[repr(float(bound))] = cumulative
            out["+Inf"] = cumulative + self._counts[-1]
            return {"buckets": out, "sum": self._sum, "count": self._count}


class _Noop:
    """Shared do-nothing metric returned while metrics are disabled —
    quacks like Counter, Gauge, and Histogram at once."""

    __slots__ = ()

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    value = 0.0
    count = 0
    sum = 0.0


_NOOP = _Noop()

_LabelKey = Tuple[Tuple[str, str], ...]


class _Family:
    """One named metric with its labeled children. The unlabeled child has
    the empty label key (reference-free: ``counter("x")`` and
    ``counter("x", rank=0)`` coexist under one family)."""

    __slots__ = ("name", "kind", "help", "buckets", "children")

    def __init__(self, name, kind, help_text, buckets=None):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.buckets = buckets
        self.children: Dict[_LabelKey, object] = {}

    def _make_child(self):
        if self.kind == "counter":
            return Counter()
        if self.kind == "gauge":
            return Gauge()
        return Histogram(self.buckets)


def _label_key(labels: dict) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Registry:
    """Lock-safe collection of metric families."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # ------------------------------------------------------------ accessors

    def _child(self, name, kind, help_text, buckets, labels):
        if not _enabled:
            return _NOOP
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    name, kind, help_text, buckets
                )
            elif fam.kind != kind:
                raise ValueError(
                    f"metric '{name}' already registered as {fam.kind}, "
                    f"requested as {kind}"
                )
            child = fam.children.get(key)
            if child is None:
                child = fam.children[key] = fam._make_child()
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        """The counter child for ``(name, labels)``, created on first use."""
        return self._child(name, "counter", help, None, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._child(name, "gauge", help, None, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  **labels) -> Histogram:
        """The histogram child for ``(name, labels)``. ``buckets`` applies
        on family creation only (children share the family's bounds)."""
        return self._child(
            name, "histogram", help,
            tuple(buckets) if buckets else DEFAULT_LATENCY_BUCKETS, labels,
        )

    # ------------------------------------------------------------- readers

    def snapshot(self) -> dict:
        """Point-in-time copy of every family::

            {name: {"type": "counter"|"gauge"|"histogram", "help": str,
                    "samples": {"" | "k=v,k2=v2": value-or-hist-dict}}}

        Counter/gauge samples are floats; histogram samples are
        ``{"buckets": {le: cumulative_count, ..., "+Inf": n},
        "sum": float, "count": int}``.
        """
        with self._lock:
            fams = [
                (f, list(f.children.items()))
                for f in self._families.values()
            ]
        out = {}
        for fam, children in fams:
            samples = {
                ",".join(f"{k}={v}" for k, v in key): child._sample()
                for key, child in children
            }
            out[fam.name] = {
                "type": fam.kind, "help": fam.help, "samples": samples
            }
        return out

    def value(self, name: str, **labels):
        """One sample, or None when the metric/child does not exist."""
        with self._lock:
            fam = self._families.get(name)
            child = fam.children.get(_label_key(labels)) if fam else None
        return None if child is None else child._sample()

    def summary(self, snap: Optional[dict] = None) -> str:
        """Human-readable dump (what ``MetricsCallback`` logs every N
        steps)."""
        snap = self.snapshot() if snap is None else snap
        lines = []
        for name in sorted(snap):
            fam = snap[name]
            for key in sorted(fam["samples"]):
                sample = fam["samples"][key]
                label = f"{name}{{{key}}}" if key else name
                if fam["type"] == "histogram":
                    count = sample["count"]
                    mean = sample["sum"] / count if count else 0.0
                    lines.append(
                        f"{label:<52} count={count} mean={mean:.6g} "
                        f"sum={sample['sum']:.6g}"
                    )
                else:
                    lines.append(f"{label:<52} {sample:.6g}")
        return "\n".join(lines) if lines else "(no metrics recorded)"

    def reset(self) -> None:
        """Drop every family (tests / per-run isolation)."""
        with self._lock:
            self._families.clear()


#: default process-wide registry (what ``hvd.metrics.*`` operates on)
REGISTRY = Registry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
snapshot = REGISTRY.snapshot
value = REGISTRY.value
summary = REGISTRY.summary
reset = REGISTRY.reset
