"""Declarative SLO registry with deterministic burn-rate math (ISSUE 16).

Objectives are declared in ``HOROVOD_SLO`` (or programmatically via
:func:`configure`) as comma-separated ``name<threshold`` pairs::

    HOROVOD_SLO="ttft_p99<0.5s,step_time_p99<2.0,error_rate<0.01"

An objective name is a **series** plus an optional quantile suffix:

- series: ``ttft`` / ``tpot`` / ``e2e`` / ``queue_wait`` (fed per
  request/token by :mod:`~horovod_tpu.observability.reqtrace`),
  ``step_time`` (fed per dispatched step by the training-step wrapper),
  ``error_rate`` (fed per completed request: 1.0 on error, 0.0 on ok),
  ``staleness`` / ``data_wait`` (sampled from the metrics-registry
  gauges ``serving_staleness_seconds`` / ``data_wait_seconds_recent``
  by :func:`sample_gauges`, called once per training step).
- quantile suffix ``_p50``/``_p90``/``_p99``/``_p999`` sets the error
  **budget**: ``ttft_p99<0.5`` means "at most 1% of requests may take
  longer than 0.5 s". Without a suffix the budget is 1% ; for
  ``error_rate`` the budget IS the threshold (``error_rate<0.01`` =
  at most 1% of requests may error) and a sample violates when it is
  an error.

**Burn-rate math is counted in observations (steps/requests), never
wall clock**, so drills pin exactly: each objective keeps a fast window
(``HOROVOD_SLO_FAST_WINDOW``, default 16 observations) and a slow
window (``HOROVOD_SLO_SLOW_WINDOW``, default 64) of violation bits.
``burn = violating_fraction / budget`` per window (the standard
multi-window burn-rate alerting shape); the objective **burns** when
the fast window is full and BOTH windows' burn rates reach
``HOROVOD_SLO_BURN_THRESHOLD`` (default 1.0 — consuming budget exactly
at the sustainable rate). A burning objective feeds
:func:`horovod_tpu.resilience.health.record_slo_burn` (HEALTHY →
SUSPECT with the objective named, escalating to DEGRADED like every
other strike source) and the ``slo_burn_rate{objective=}`` /
``slo_budget_remaining{objective=}`` gauges that ride the ``/fleet``
plane.

:meth:`SLORegistry.judge_canary` is the rollout controller's gate: the
canary arm's completion window is evaluated against every serving-side
objective, judged **relative to the stable arm's live baseline** (a
globally slow system does not indict the canary) — replacing the
rollout's bespoke error-rate/latency-ratio pair.

stdlib-only; all registry state is lock-guarded.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from horovod_tpu.observability import metrics as _metrics

__all__ = [
    "SLO_ENV",
    "FAST_WINDOW_ENV",
    "SLOW_WINDOW_ENV",
    "BURN_THRESHOLD_ENV",
    "SERIES",
    "Objective",
    "SLORegistry",
    "parse_spec",
    "configure",
    "reset",
    "default",
    "observe",
    "sample_gauges",
    "status",
]

SLO_ENV = "HOROVOD_SLO"
FAST_WINDOW_ENV = "HOROVOD_SLO_FAST_WINDOW"
SLOW_WINDOW_ENV = "HOROVOD_SLO_SLOW_WINDOW"
BURN_THRESHOLD_ENV = "HOROVOD_SLO_BURN_THRESHOLD"

#: series an objective may target, and where each is fed from
SERIES = (
    "ttft",        # reqtrace.on_first_token
    "tpot",        # reqtrace.on_token
    "e2e",         # reqtrace.on_finish
    "queue_wait",  # reqtrace.on_admit
    "step_time",   # training.InstrumentedStep
    "error_rate",  # reqtrace.on_finish / on_reject (1.0 error, 0.0 ok)
    "staleness",   # sample_gauges <- serving_staleness_seconds
    "data_wait",   # sample_gauges <- data_wait_seconds_recent
)

#: gauge families sample_gauges() polls per series (first present wins)
_GAUGE_SOURCES = {
    "staleness": ("serving_staleness_seconds",
                  "serving_subscribe_staleness_seconds"),
    "data_wait": ("data_wait_seconds_recent",),
}

_QUANTILE_BUDGETS = {"p50": 0.5, "p90": 0.1, "p99": 0.01, "p999": 0.001}


class Objective:
    """One declared objective: a violation-bit stream over two counted
    windows, with deterministic burn-rate arithmetic."""

    def __init__(self, name: str, series: str, threshold: float,
                 budget: float, *, fast: int, slow: int):
        self.name = name
        self.series = series
        self.threshold = float(threshold)
        self.budget = float(budget)
        self.fast: deque = deque(maxlen=max(1, int(fast)))
        self.slow: deque = deque(maxlen=max(1, int(slow)))

    def violates(self, value: float) -> bool:
        return float(value) > self.threshold

    def observe(self, value: float) -> None:
        bad = self.violates(value)
        self.fast.append(bad)
        self.slow.append(bad)

    def burn(self, window: deque) -> float:
        """``violating_fraction / budget`` over one window (0.0 while the
        window is empty; infinite on any violation when the budget is
        zero)."""
        if not window:
            return 0.0
        frac = sum(1 for b in window if b) / len(window)
        if self.budget <= 0.0:
            return float("inf") if frac > 0 else 0.0
        return frac / self.budget

    def budget_remaining(self) -> float:
        """Fraction of the slow window's error budget still unspent,
        clamped to [0, 1]."""
        if not self.slow:
            return 1.0
        spent = self.burn(self.slow)
        if spent == float("inf"):
            return 0.0
        return max(0.0, min(1.0, 1.0 - spent))

    def burning(self, threshold: float) -> bool:
        """Multi-window verdict: the FAST window must be full (no
        verdicts off a cold start) and both windows must burn at or past
        `threshold`."""
        return (len(self.fast) == self.fast.maxlen
                and self.burn(self.fast) >= threshold
                and self.burn(self.slow) >= threshold)


def parse_spec(spec: str, *, fast: int, slow: int) -> List[Objective]:
    """``"ttft_p99<0.5s,error_rate<0.01"`` → objectives. Unknown series
    raise ``ValueError`` (typos fail loudly, like the chaos grammar)."""
    out: List[Objective] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, sep, thresh_s = item.partition("<")
        if not sep:
            raise ValueError(
                f"{SLO_ENV}: expected name<threshold, got {item!r}")
        name = name.strip()
        thresh_s = thresh_s.strip()
        if thresh_s.endswith("s"):
            thresh_s = thresh_s[:-1]
        threshold = float(thresh_s)
        series, budget = name, 0.01
        base, _sep2, suffix = name.rpartition("_")
        if suffix in _QUANTILE_BUDGETS and base:
            series = base
            budget = _QUANTILE_BUDGETS[suffix]
        if series == "error_rate":
            budget = threshold
            threshold = 0.5  # a sample is 1.0 (error) or 0.0 (ok)
        if series not in SERIES:
            raise ValueError(
                f"{SLO_ENV}: unknown objective series {series!r} in "
                f"{name!r} (known: {', '.join(SERIES)})")
        out.append(Objective(name, series, threshold, budget,
                             fast=fast, slow=slow))
    return out


class SLORegistry:
    """The evaluator: routes observations to objectives, publishes the
    burn gauges, strikes the health machine when an objective burns."""

    def __init__(self, spec: str = "", *,
                 fast_window: Optional[int] = None,
                 slow_window: Optional[int] = None,
                 burn_threshold: Optional[float] = None):
        self.fast_window = int(
            fast_window if fast_window is not None
            else os.environ.get(FAST_WINDOW_ENV, "16"))
        self.slow_window = int(
            slow_window if slow_window is not None
            else os.environ.get(SLOW_WINDOW_ENV, "64"))
        self.burn_threshold = float(
            burn_threshold if burn_threshold is not None
            else os.environ.get(BURN_THRESHOLD_ENV, "1.0"))
        self._lock = threading.Lock()
        self._objectives = parse_spec(
            spec, fast=self.fast_window, slow=self.slow_window)
        self._by_series: Dict[str, List[Objective]] = {}
        for o in self._objectives:
            self._by_series.setdefault(o.series, []).append(o)
        # strike cadence: one strike on entry into burning, then one
        # every fast_window observations while it stays burning (bounded
        # and counted in observations — deterministic under drill)
        self._burning: Dict[str, bool] = {}
        self._since_strike: Dict[str, int] = {}

    @property
    def objectives(self) -> List[Objective]:
        return list(self._objectives)

    def observe(self, series: str, value: float) -> None:
        """Feed one observation to every objective on `series`."""
        targets = self._by_series.get(series)
        if not targets:
            return
        strikes: List[Tuple[str, str]] = []
        with self._lock:
            for o in targets:
                o.observe(value)
                burning = o.burning(self.burn_threshold)
                window = (f"{len(o.fast)}/{o.fast.maxlen} fast, "
                          f"{len(o.slow)}/{o.slow.maxlen} slow obs")
                if burning:
                    self._since_strike[o.name] = \
                        self._since_strike.get(o.name, 0) + 1
                    if (not self._burning.get(o.name)
                            or self._since_strike[o.name]
                            >= o.fast.maxlen):
                        self._since_strike[o.name] = 0
                        strikes.append((o.name, window))
                else:
                    self._since_strike[o.name] = 0
                self._burning[o.name] = burning
                self._publish(o)
        for name, window in strikes:
            from horovod_tpu.resilience import health as _health

            _health.record_slo_burn(name, window)

    def _publish(self, o: Objective) -> None:
        # caller holds self._lock; registry children have their own lock
        if not _metrics.enabled():
            return
        burn = o.burn(o.fast)
        if burn == float("inf"):
            burn = -1.0  # JSON-safe sentinel for "budget is zero"
        _metrics.gauge(
            "slo_burn_rate",
            help="fast-window error-budget burn rate per objective "
                 "(1.0 = spending exactly the budget; -1 = zero-budget "
                 "objective violated)",
            objective=o.name,
        ).set(burn)
        _metrics.gauge(
            "slo_budget_remaining",
            help="unspent fraction of the slow-window error budget per "
                 "objective",
            objective=o.name,
        ).set(o.budget_remaining())

    def sample_gauges(self) -> None:
        """Poll the gauge-sourced series (subscriber staleness, input
        data-wait) out of the metrics registry — called once per
        training step so these objectives are counted in steps."""
        for series, sources in _GAUGE_SOURCES.items():
            if series not in self._by_series:
                continue
            for fam in sources:
                v = _metrics.value(fam)
                if isinstance(v, (int, float)):
                    self.observe(series, float(v))
                    break

    def status(self) -> List[dict]:
        """Per-objective snapshot (the ``hvd_slo`` CLI's live view)."""
        with self._lock:
            out = []
            for o in self._objectives:
                out.append({
                    "objective": o.name,
                    "series": o.series,
                    "threshold": o.threshold,
                    "budget": o.budget,
                    "fast_burn": o.burn(o.fast),
                    "slow_burn": o.burn(o.slow),
                    "budget_remaining": o.budget_remaining(),
                    "burning": o.burning(self.burn_threshold),
                    "observations": len(o.slow),
                })
            return out

    # -------------------------------------------------- the rollout gate

    def judge_canary(self, canary: Dict[str, object],
                     stable: Dict[str, object]) -> Optional[Tuple[str, str]]:
        """Evaluate the canary arm's completion window (an
        ``reqtrace.arm_window`` dict) against every serving-side
        objective, relative to the stable arm's live baseline. Returns
        ``(objective_name, detail)`` for the first burning objective, or
        None when the canary is clean."""
        for o in self._objectives:
            if o.series in ("ttft", "tpot", "e2e", "queue_wait"):
                values = list(canary.get(o.series) or [])
                if not values:
                    continue
                frac = sum(1 for v in values if o.violates(v)) \
                    / len(values)
                burn = (float("inf") if frac > 0 else 0.0) \
                    if o.budget <= 0 else frac / o.budget
                if burn < self.burn_threshold:
                    continue
                # live-baseline guard: only indict the canary when it is
                # actually worse than what stable serves right now
                base = list(stable.get(o.series) or [])
                if base:
                    cq = _nearest_rank(values, 1.0 - o.budget)
                    sq = _nearest_rank(base, 1.0 - o.budget)
                    if cq is not None and sq is not None and cq <= sq:
                        continue
                return (o.name,
                        f"{frac:.0%} of {len(values)} canary "
                        f"{o.series} samples over {o.threshold:g}s "
                        f"(budget {o.budget:g})")
            elif o.series == "error_rate":
                done = int(canary.get("done") or 0)
                if done <= 0:
                    continue
                rate = int(canary.get("errors") or 0) / done
                if o.budget <= 0:
                    if rate > 0:
                        return (o.name,
                                f"error rate {rate:.2f} with a zero "
                                f"error budget over {done} canary "
                                f"requests")
                    continue
                if rate / o.budget >= self.burn_threshold:
                    return (o.name,
                            f"error rate {rate:.2f} > budget "
                            f"{o.budget:g} over {done} canary requests")
        return None


def _nearest_rank(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    import math

    vs = sorted(values)
    return vs[min(len(vs) - 1, max(0, math.ceil(q * len(vs)) - 1))]


# ------------------------------------------------- module-level default

_default_lock = threading.Lock()
_default: Optional[SLORegistry] = None


def default() -> SLORegistry:
    """The process-wide registry, parsed lazily from ``HOROVOD_SLO``."""
    global _default
    with _default_lock:
        if _default is None:
            _default = SLORegistry(os.environ.get(SLO_ENV, ""))
        return _default


def configure(spec: Optional[str], **kwargs) -> None:
    """Install the default registry programmatically (tests, drills);
    ``configure(None)`` clears every objective regardless of the env."""
    global _default
    with _default_lock:
        _default = SLORegistry(spec or "", **kwargs)


def reset() -> None:
    """Forget the default registry; the env is re-parsed on next use."""
    global _default
    with _default_lock:
        _default = None


def observe(series: str, value: float) -> None:
    """Feed the default registry (reqtrace / training-step hot path —
    a no-op dict lookup when no objective targets `series`)."""
    default().observe(series, value)


def sample_gauges() -> None:
    """Poll gauge-sourced objectives on the default registry."""
    default().sample_gauges()


def status() -> List[dict]:
    return default().status()
