"""Fleet-wide metric aggregation over the rendezvous KV plane.

PR 1's registry is strictly per-process: each rank keeps its own counters
and only rank 0's view is exported. This module is the distributed half —
the rebuild of what Horovod's coordinator knows implicitly through the
negotiation protocol (it sees every rank's requests; PAPER.md L4) but never
exposes:

- :class:`MetricsPublisher` — every rank periodically publishes its
  :func:`~horovod_tpu.observability.metrics.snapshot` (plus its recent
  collective-arrival ring and clock-sync info) to the rendezvous KV under
  ``/obs/snap/<rank>`` with a TTL. The WAL-backed
  :class:`~horovod_tpu.run.rendezvous.KVStoreServer` (PR 6) is the
  transport: a KV restart replays the last snapshots, and a rank that
  stops publishing *tombstones* instead of vanishing.
- :class:`FleetAggregator` — rank 0 (or any observer) merges the
  snapshots into fleet series: per-metric ``min/mean/max/p99`` across
  ranks plus ``rank``-labeled raw series; histograms merge bucket-wise.
  Dead ranks (TTL-expired snapshots, HTTP 410 / tombstone) are SURFACED
  in ``dead_ranks`` — a rank that stopped reporting is a finding, not a
  smaller denominator. Correlated collective arrivals are unioned by
  ``(step, gen, seq)`` and fed through
  :func:`horovod_tpu.observability.straggler.attribute`, so the fleet view
  names the straggler.

The rank-0 HTTP endpoint grows ``/fleet`` (Prometheus exposition of the
fleet series) and ``/fleet.json`` once an aggregator is registered;
``tools/hvd_top.py`` renders either live.

stdlib-only at import (the rendezvous client is imported lazily — this
module must stay importable from collection-time contexts, like the rest
of the package).
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from horovod_tpu.observability import clock as _clock
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import straggler as _straggler

logger = logging.getLogger("horovod_tpu.observability")

__all__ = [
    "MetricsPublisher",
    "FleetAggregator",
    "merge_snapshots",
    "to_prometheus_fleet",
    "set_aggregator",
    "get_aggregator",
    "fleet_json",
    "fleet_prometheus",
    "SNAP_SCOPE",
]

#: KV namespace the publishers write under (``<scope>/<rank>``)
SNAP_SCOPE = "/obs/snap"

#: default lease on a published snapshot: miss ~3 publish intervals and the
#: rank tombstones in the fleet view
DEFAULT_TTL_FACTOR = 3.0
DEFAULT_INTERVAL = 10.0


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Linear-interpolated quantile of an ascending sequence."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = q * (len(sorted_vals) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


class MetricsPublisher:
    """Publish this rank's metrics snapshot to the rendezvous KV on a
    cadence.

    `kv` is anything with ``put(key, bytes, ttl=...)`` — the in-process
    :class:`~horovod_tpu.run.rendezvous.KVStoreServer` (single-controller)
    or a :class:`~horovod_tpu.run.rendezvous.KVStoreClient` (each launched
    worker builds one from ``HVD_RUN_KV_ADDR``/``HVD_RUN_KV_PORT``).
    :meth:`publish_once` is the deterministic spelling tests and step
    hooks use; :meth:`start` runs it on a daemon thread every `interval`
    seconds. The TTL (default ``3 × interval``) is the fleet's
    failure-detection horizon: a rank that stops publishing shows up DEAD
    in the aggregator, not absent."""

    def __init__(self, kv, rank: int, *, scope: str = SNAP_SCOPE,
                 interval: float = DEFAULT_INTERVAL,
                 ttl: Optional[float] = None,
                 arrival_window: Optional[int] = None):
        self._kv = kv
        self._rank = int(rank)
        self._scope = "/" + scope.strip("/")
        self._interval = float(interval)
        self._ttl = (
            float(ttl) if ttl is not None
            else DEFAULT_TTL_FACTOR * self._interval
        )
        self._arrival_window = arrival_window
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # sync the clock up front, not at the first publish an interval
        # away: export_recent corrects ring timestamps retroactively, but
        # an early estimate tightens the first published window too
        self._ensure_clock_sync()

    @property
    def key(self) -> str:
        return f"{self._scope}/{self._rank}"

    def _ensure_clock_sync(self) -> None:
        """First publication estimates this rank's clock offset against the
        KV it publishes through (once; elastic resizes re-estimate via the
        coordinator). Without this, multi-host arrival timestamps would
        ride raw per-host monotonic clocks — whose origins differ by host
        uptime — and attribution would flag a permanent false straggler.
        Best-effort: a failed probe leaves offset 0 rather than blocking
        publication."""
        if _clock.error_bound() is not None:
            return
        try:
            _clock.refresh_from_kv(self._kv, rank=self._rank)
        except Exception as e:
            logger.debug("clock sync against the KV failed: %s", e)

    def payload(self) -> dict:
        self._ensure_clock_sync()
        return {
            "rank": self._rank,
            "sent_monotonic": time.monotonic() + _clock.offset(),
            "clock": _clock.info(),
            "metrics": _metrics.snapshot(),
            "arrivals": _straggler.export_recent(self._arrival_window),
        }

    def publish_once(self) -> None:
        blob = json.dumps(self.payload()).encode()
        self._kv.put(self.key, blob, ttl=self._ttl)
        if _metrics.enabled():
            _metrics.counter(
                "fleet_snapshots_published",
                help="metric snapshots published to the rendezvous KV",
            ).inc()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self._interval):
                try:
                    self.publish_once()
                except Exception as e:
                    # observability must never take down training; the TTL
                    # expiring is itself the failure signal
                    logger.debug("metrics publish failed: %s", e)

        self._thread = threading.Thread(
            target=_loop, name="hvd-metrics-publish", daemon=True)
        self._thread.start()

    def stop(self, final_publish: bool = True) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
        if final_publish:
            try:
                self.publish_once()
            except Exception as e:
                logger.debug("final metrics publish failed: %s", e)


def merge_snapshots(snaps: Dict[int, dict]) -> dict:
    """Fold per-rank :func:`metrics.snapshot` dicts into fleet families.

    Counters/gauges become ``{"ranks": {rank: v}, "min", "mean", "max",
    "p99"}`` per labeled child; histograms merge bucket-wise (families fix
    their bounds at creation, so same-name buckets line up) with a
    ``p99`` estimated from the merged cumulative counts (upper bucket
    bound — conservative)."""
    fleet: dict = {}
    for rank in sorted(snaps):
        for name, fam in (snaps[rank] or {}).items():
            slot = fleet.setdefault(
                name,
                {"type": fam["type"], "help": fam.get("help", ""),
                 "samples": {}},
            )
            if slot["type"] != fam["type"]:
                continue  # conflicting registration; skip rather than mix
            for key, sample in fam.get("samples", {}).items():
                if fam["type"] == "histogram":
                    h = slot["samples"].setdefault(
                        key, {"buckets": {}, "sum": 0.0, "count": 0})
                    for le, cum in sample.get("buckets", {}).items():
                        h["buckets"][le] = h["buckets"].get(le, 0) + cum
                    h["sum"] += float(sample.get("sum", 0.0))
                    h["count"] += int(sample.get("count", 0))
                else:
                    s = slot["samples"].setdefault(key, {"ranks": {}})
                    s["ranks"][str(rank)] = float(sample)
    for name, fam in fleet.items():
        for key, s in fam["samples"].items():
            if fam["type"] == "histogram":
                s["p99"] = _hist_p99(s)
            else:
                vals = sorted(s["ranks"].values())
                s["min"] = vals[0]
                s["max"] = vals[-1]
                s["mean"] = sum(vals) / len(vals)
                s["p99"] = _percentile(vals, 0.99)
    return fleet


def _hist_p99(h: dict) -> Optional[float]:
    count = h.get("count", 0)
    if not count:
        return None
    target = 0.99 * count
    finite = [
        (float(le), cum) for le, cum in h["buckets"].items() if le != "+Inf"
    ]
    for le, cum in sorted(finite):
        if cum >= target:
            return le
    # target falls in the +Inf tail: report the LARGEST finite bound (a
    # floor), not whichever bucket dict order put last
    return max(le for le, _ in finite) if finite else None


class FleetAggregator:
    """Collect every rank's published snapshot and serve the merged view.

    `kv` is the in-process :class:`KVStoreServer` (liveness read straight
    off the store: live keys + tombstones) or a :class:`KVStoreClient`
    probing ranks ``0..world-1`` (a tombstoned snapshot answers HTTP 410 →
    the rank is DEAD; 404 → never published). Pass `world` whenever more
    than one process publishes — including server-backed setups — so
    straggler attribution can defer a collective until EVERY rank's
    arrival landed (the slow rank's snapshot is the one most likely still
    in flight). Construction registers the instance as the process
    default so the rank-0 HTTP endpoint can serve
    ``/fleet``/``/fleet.json`` (``register=False`` opts out)."""

    def __init__(self, kv, *, world: Optional[int] = None,
                 scope: str = SNAP_SCOPE, register: bool = True):
        if world is None and not (
            hasattr(kv, "live_keys") and hasattr(kv, "dead_keys")
        ):
            # a probing client cannot enumerate the store: without a world
            # it would silently aggregate zero ranks forever
            raise ValueError(
                "FleetAggregator over a KV client needs world=<rank "
                "count> to know which /obs/snap/<rank> keys to probe "
                "(a KVStoreServer enumerates the store itself)"
            )
        self._kv = kv
        self._world = world
        self._scope = "/" + scope.strip("/")
        self._last: Optional[dict] = None
        if register:
            set_aggregator(self)

    # ------------------------------------------------------------- fetching

    def _rank_of(self, key: str) -> Optional[int]:
        tail = key[len(self._scope) + 1:]
        try:
            return int(tail)
        except ValueError:
            return None

    def _fetch_all(self) -> Tuple[Dict[int, dict], List[int]]:
        """{rank: payload}, dead_ranks — via store enumeration (server) or
        per-rank probing (client)."""
        from horovod_tpu.run.rendezvous import DeadRankError

        snaps: Dict[int, dict] = {}
        dead: List[int] = []
        if hasattr(self._kv, "live_keys") and hasattr(self._kv, "dead_keys"):
            prefix = self._scope + "/"
            for key in self._kv.live_keys(prefix):
                rank = self._rank_of(key)
                if rank is None:
                    continue
                blob = self._kv.get(key)
                if blob is not None:
                    snaps[rank] = self._decode(blob)
            for key in self._kv.dead_keys():
                if key.startswith(prefix):
                    rank = self._rank_of(key)
                    if rank is not None and rank not in snaps:
                        dead.append(rank)
        else:
            world = self._world or 0
            for rank in range(world):
                try:
                    blob = self._kv.get(f"{self._scope}/{rank}")
                except DeadRankError:
                    dead.append(rank)
                    continue
                if blob is not None:
                    snaps[rank] = self._decode(blob)
        return snaps, sorted(dead)

    @staticmethod
    def _decode(blob: bytes) -> dict:
        try:
            return json.loads(blob)
        except ValueError:
            return {}

    # ------------------------------------------------------------ the merge

    def collect(self) -> dict:
        """One aggregation pass: fetch, merge, attribute, remember."""
        snaps, dead = self._fetch_all()
        metric_snaps = {
            r: p.get("metrics", {}) for r, p in snaps.items()
        }
        merged_arrivals = _straggler.merge_arrival_exports(
            p.get("arrivals") for p in snaps.values()
        )
        # single-controller snapshots carry COMPLETE arrival sets (one
        # process simulates every rank), so a key needs only the default
        # 2 arrivals; with several publishing processes a key is deferred
        # until the FULL world's arrivals landed — the straggler's own
        # snapshot is the one most likely still in flight, so scoring
        # against the published-so-far subset would systematically miss
        # its decisive late entry. `world` (pass it even with a
        # server-backed store) is authoritative; without it the
        # live+dead union is the best available floor.
        expected = None
        if self._world:
            expected = self._world
        elif len(snaps) > 1:
            expected = len(snaps) + len(dead)
        # per-rank input waits ride each snapshot as the
        # data_wait_seconds_recent gauge: hand them to attribution so a
        # named straggler is classified input- vs compute-bound on the
        # fleet view too (ISSUE 15)
        data_waits = {}
        for r, m in metric_snaps.items():
            fam = m.get("data_wait_seconds_recent") or {}
            v = fam.get("samples", {}).get("")
            if v is not None:
                try:
                    data_waits[int(r)] = float(v)
                except (TypeError, ValueError):
                    continue
        straggler = _straggler.attribute(
            merged_arrivals, expected_ranks=expected,
            data_waits=data_waits or None,
        )
        out = {
            "collected_at": time.time(),
            "ranks": sorted(snaps),
            "dead_ranks": dead,
            "clock": {
                str(r): p.get("clock") for r, p in snaps.items()
            },
            "metrics": merge_snapshots(metric_snaps),
            "straggler": straggler,
        }
        self._last = out
        if _metrics.enabled():
            _metrics.counter(
                "fleet_aggregations",
                help="fleet aggregation passes completed",
            ).inc()
            _metrics.gauge(
                "fleet_ranks", help="ranks with a live published snapshot",
            ).set(len(snaps))
            _metrics.gauge(
                "fleet_dead_ranks",
                help="ranks whose snapshot lease expired (TTL/tombstone)",
            ).set(len(dead))
        return out

    @property
    def last(self) -> Optional[dict]:
        return self._last


def to_prometheus_fleet(agg: dict) -> str:
    """Render one :meth:`FleetAggregator.collect` result as Prometheus
    text exposition: ``fleet_<name>{stat=...}`` summary gauges +
    rank-labeled raw series per scalar family, merged ``_bucket``/``_sum``/
    ``_count`` series (with their own explicit ``# TYPE ... histogram``
    line) per histogram family, and ``fleet_rank_alive`` liveness. Every
    family — the fleet synthetics included — gets a ``# HELP`` line beside
    its ``# TYPE``, so a Prometheus UI explains the fleet series exactly
    like the per-process ones."""
    from horovod_tpu.observability.exporters import (
        _fmt, _prom_labels, _prom_name,
    )

    lines: List[str] = []

    def _help(pname: str, text: str) -> None:
        if text:
            esc = text.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {pname} {esc}")

    metrics = agg.get("metrics", {})
    for name in sorted(metrics):
        fam = metrics[name]
        pname = _prom_name(name)
        if fam["type"] == "histogram":
            _help(f"fleet_{pname}",
                  (fam.get("help") or "") + " (fleet-merged across ranks)")
            lines.append(f"# TYPE fleet_{pname} histogram")
            for key in sorted(fam["samples"]):
                s = fam["samples"][key]
                for le, cum in sorted(
                    s["buckets"].items(),
                    key=lambda kv: (kv[0] == "+Inf", _le_sort(kv[0])),
                ):
                    lines.append(
                        f"fleet_{pname}_bucket"
                        f"{_prom_labels(key, 'le=' + _q(le))} {cum}"
                    )
                lines.append(
                    f"fleet_{pname}_sum{_prom_labels(key)} {_fmt(s['sum'])}"
                )
                lines.append(
                    f"fleet_{pname}_count{_prom_labels(key)} {s['count']}"
                )
                if s.get("p99") is not None:
                    lines.append(
                        f"fleet_{pname}_p99{_prom_labels(key)} "
                        f"{_fmt(s['p99'])}"
                    )
        else:
            _help(f"fleet_{pname}",
                  (fam.get("help") or "")
                  + " (min/mean/max/p99 across ranks)")
            lines.append(f"# TYPE fleet_{pname} gauge")
            for key in sorted(fam["samples"]):
                s = fam["samples"][key]
                for stat in ("min", "mean", "max", "p99"):
                    lines.append(
                        f"fleet_{pname}"
                        f"{_prom_labels(key, 'stat=' + _q(stat))} "
                        f"{_fmt(s[stat])}"
                    )
            _help(pname, fam.get("help") or "")
            lines.append(f"# TYPE {pname} {fam['type']}")
            for key in sorted(fam["samples"]):
                for rank in sorted(
                    fam["samples"][key]["ranks"], key=int
                ):
                    v = fam["samples"][key]["ranks"][rank]
                    extra = None if "rank=" in key else "rank=" + _q(rank)
                    lines.append(
                        f"{pname}{_prom_labels(key, extra)} {_fmt(v)}"
                    )
    _help("fleet_rank_alive",
          "1 while the rank's published snapshot lease is live, 0 once "
          "it TTL-expired or tombstoned")
    lines.append("# TYPE fleet_rank_alive gauge")
    for r in agg.get("ranks", []):
        lines.append(f'fleet_rank_alive{{rank="{r}"}} 1')
    for r in agg.get("dead_ranks", []):
        lines.append(f'fleet_rank_alive{{rank="{r}"}} 0')
    s = agg.get("straggler")
    if s:
        # distinct family names: the aggregated per-rank `straggler_rank`
        # series above already claims that name's TYPE line
        _help("fleet_straggler_detected_rank",
              "rank the fleet-side arrival correlation currently "
              "attributes the straggler to")
        lines.append("# TYPE fleet_straggler_detected_rank gauge")
        lines.append(f"fleet_straggler_detected_rank {s['rank']}")
        _help("fleet_straggler_detected_spread_seconds",
              "arrival spread behind the rest of the fleet at the "
              "attributed collective")
        lines.append("# TYPE fleet_straggler_detected_spread_seconds gauge")
        lines.append(
            "fleet_straggler_detected_spread_seconds "
            f"{_fmt(s['spread_seconds'])}"
        )
    return "\n".join(lines) + "\n" if lines else ""


def _q(v) -> str:
    from horovod_tpu.observability.exporters import _quote_label_value

    return _quote_label_value(v)


def _le_sort(le: str) -> float:
    try:
        return float(le)
    except ValueError:
        return math.inf


# ------------------------------------------------- process-default instance

_default_lock = threading.Lock()
_default: Optional[FleetAggregator] = None


def set_aggregator(agg: Optional[FleetAggregator]) -> None:
    """Register the aggregator the rank-0 HTTP endpoint serves from
    (``/fleet``, ``/fleet.json``); ``None`` unregisters."""
    global _default
    with _default_lock:
        _default = agg


def get_aggregator() -> Optional[FleetAggregator]:
    return _default


def fleet_json() -> Optional[str]:
    """Fresh aggregation pass rendered as JSON, or None without a
    registered aggregator (the ``/fleet.json`` handler)."""
    agg = get_aggregator()
    if agg is None:
        return None
    return json.dumps(agg.collect(), indent=1)


def fleet_prometheus() -> Optional[str]:
    """Fresh aggregation pass rendered as exposition text, or None without
    a registered aggregator (the ``/fleet`` handler)."""
    agg = get_aggregator()
    if agg is None:
        return None
    return to_prometheus_fleet(agg.collect())
