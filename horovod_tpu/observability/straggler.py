"""Straggler attribution: correlate collective arrivals across ranks and
name the rank that everyone else is waiting for.

Horovod's coordinator stall-check is the reference instrument (PAPER.md L4:
the negotiation protocol means rank 0 KNOWS which ranks are late for which
tensor); this module rebuilds it for the TPU-native stack from the
observability side:

- every eager collective dispatch gets a **correlation key** ``(step,
  elastic generation, per-op seq)`` — ranks dispatch collectives in the
  same program order, so the key needs no negotiation to agree across
  processes (``seq`` resets at each step boundary, ``generation`` bumps on
  elastic resizes so keys never collide across epochs);
- each dispatch records an **arrival timestamp** on the KV-server timebase
  (local monotonic + :func:`horovod_tpu.observability.clock.offset`) into a
  bounded ring, and mirrors it into the host trace as an event on the
  ``rank<r>`` pid lane carrying the key in its ``args`` — the merged
  timeline's per-rank rows;
- :func:`attribute` folds correlated arrival sets (2+ ranks) into
  ``collective_arrival_spread_seconds`` (histogram) + ``straggler_rank``
  (gauge) and, when ONE rank is last by ≥ ``HOROVOD_STRAGGLER_THRESHOLD``
  for ``HOROVOD_STRAGGLER_PERSIST`` consecutive correlated collectives,
  feeds :func:`horovod_tpu.resilience.health.record_straggler` — the
  health machine goes SUSPECT with the rank named in its reason.

Topology note: in the single-controller SPMD case one process dispatches on
behalf of every rank, so per-rank arrivals are *simulated* — identical
timestamps, except a rank charged with ``HOROVOD_CHAOS=rank_slow=<rank>:<s>``
arrives ``<s>`` late (the process really sleeps, so step time moves too —
``bench.py --straggler-ab`` measures exactly that). Multi-process ranks each
record only their OWN arrival; the rank-0
:class:`~horovod_tpu.observability.aggregate.FleetAggregator` unions the
rings by key before attribution.

stdlib-only at import (resilience/chaos/health are imported lazily at call
time; the caller passes world/rank identity in, so this module never
touches the data plane).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import trace as _trace
from horovod_tpu.observability import clock as _clock

__all__ = [
    "set_step",
    "set_generation",
    "collective_begin",
    "last_key",
    "span_args",
    "export_recent",
    "attribute",
    "merge_arrival_exports",
    "note_data_wait",
    "data_waits",
    "reset",
    "threshold",
    "persist_after",
]

#: seconds of arrival spread below which nobody is called a straggler
THRESHOLD_ENV = "HOROVOD_STRAGGLER_THRESHOLD"
#: consecutive attributed collectives one rank must trail before the
#: health machine is fed (SUSPECT)
PERSIST_ENV = "HOROVOD_STRAGGLER_PERSIST"
#: arrival-ring capacity (recent collectives kept for aggregation)
WINDOW_ENV = "HOROVOD_STRAGGLER_WINDOW"

_lock = threading.Lock()
_step = 0
_generation = 0
_seq = 0
_last_key: Optional[Tuple[int, int, int]] = None
_window_cache: Optional[int] = None
_ring: "collections.deque" = collections.deque(maxlen=256)

# attribution state (lives on whichever process runs attribute(), rank 0).
# Its own lock: attribute() is reachable concurrently from the rank-0
# aggregation loop AND ThreadingHTTPServer /fleet handler threads — an
# unsynchronized race would double-strike health for one key.
_attr_lock = threading.Lock()
_seen_keys: "collections.OrderedDict" = collections.OrderedDict()
_streak_rank: Optional[int] = None
_streak = 0
_current: Optional[dict] = None  # latest attribution, sticky until contradicted


_threshold_cache: Optional[float] = None
_persist_cache: Optional[int] = None

# input-side attribution (ISSUE 15): the data plane notes each rank's most
# recent input-pipeline wait here; collective_begin folds it into the
# simulated arrivals (single-controller) and attribute() classifies a
# named straggler as input-bound when its wait explains the spread —
# "slow disk" vs "slow chip", today's blind spot
_data_wait: Dict[int, float] = {}


def note_data_wait(rank: int, seconds: float) -> None:
    """The input pipeline feeding `rank` made its step loop wait `seconds`
    for the latest batch (:class:`horovod_tpu.data.ResumableLoader` calls
    this per consumed batch). Zero/near-zero waits overwrite older stalls,
    so a recovered pipeline stops being attributed immediately."""
    with _lock:
        _data_wait[int(rank)] = max(0.0, float(seconds))


def data_waits() -> Dict[int, float]:
    """Most recent per-rank input waits (a copy)."""
    with _lock:
        return dict(_data_wait)


def threshold() -> float:
    """Env read cached (attribution loops call this per record while
    holding the attribution lock); :func:`reset` re-reads."""
    global _threshold_cache
    if _threshold_cache is None:
        _threshold_cache = float(os.environ.get(THRESHOLD_ENV, "0.05"))
    return _threshold_cache


def persist_after() -> int:
    global _persist_cache
    if _persist_cache is None:
        _persist_cache = max(1, int(os.environ.get(PERSIST_ENV, "3")))
    return _persist_cache


def _window() -> int:
    global _window_cache
    if _window_cache is None:
        _window_cache = max(8, int(os.environ.get(WINDOW_ENV, "256")))
    return _window_cache


def set_step(step: int) -> None:
    """Open step `step`'s correlation scope (resets the per-op seq).
    ``InstrumentedStep`` calls this per dispatched train step; explicit
    loops (tests, serving drivers) call it themselves."""
    global _step, _seq
    with _lock:
        _step = int(step)
        _seq = 0


def set_generation(gen: int) -> None:
    """Record the elastic generation (the middle key component): the
    elastic driver calls this after every resize so correlation keys never
    collide across membership epochs."""
    global _generation, _seq
    with _lock:
        _generation = int(gen)
        _seq = 0


def last_key() -> Optional[Tuple[int, int, int]]:
    """The key assigned by the most recent :func:`collective_begin` (what
    the dispatch site stamps onto its trace span)."""
    return _last_key


def span_args() -> dict:
    """``last_key`` spelled as chrome-trace span args ({} before any
    dispatch)."""
    k = _last_key
    if k is None:
        return {}
    return {"step": k[0], "gen": k[1], "seq": k[2]}


def _chaos_mod():
    from horovod_tpu.resilience import chaos

    return chaos


def _health_mod():
    from horovod_tpu.resilience import health

    return health


def collective_begin(
    op: str,
    *,
    world: int = 1,
    process_rank: int = 0,
    process_size: int = 1,
) -> Tuple[int, int, int]:
    """One eager collective is about to dispatch: assign its correlation
    key, apply any ``rank_slow`` chaos charge, and record arrivals.

    `world` is the collective's rank count (mesh data-axis size),
    `process_rank`/`process_size` the process identity — the caller
    (``ops/collective.py``) supplies them so this module stays free of the
    data plane. Returns the key."""
    global _seq, _last_key
    with _lock:
        key = (_step, _generation, _seq)
        _seq += 1
        _last_key = key
    chaos = _chaos_mod()
    slow: Optional[Tuple[int, float]] = None
    if chaos.enabled():
        slow = chaos.rank_slow()
    # _data_wait is consumed ONLY by the single-controller simulated
    # arrivals below — multi-process ranks record their real (already
    # delayed) dispatch time, and their loaders note waits every batch,
    # so probing here would permanently defeat the hot-path early
    # return. The unlocked truthiness probe keeps the common case (no
    # loader, or no stall) at one lock acquisition.
    waits: Dict[int, float] = {}
    if process_size == 1 and _data_wait:
        with _lock:
            waits = {r: w for r, w in _data_wait.items() if w > 0}
    if slow is None and not waits and not (
            _metrics.enabled() or _trace.enabled()):
        # nothing can consume an arrival record (no aggregation plane, no
        # trace) and no chaos charge to apply: keep only the seq
        # discipline — ranks must agree on keys even when one has
        # observability off — and stay off the eager hot path
        return key
    # timestamps are stored RAW-LOCAL (time.monotonic); the server-clock
    # offset is applied at export time (export_recent), so records
    # captured before the first clock sync are corrected retroactively
    # rather than baking a 0 offset in forever
    now_local = time.monotonic()
    if process_size > 1:
        # each process knows only its own arrival; the aggregator unions
        if slow is not None and slow[0] == process_rank and slow[1] > 0:
            chaos.record_injection("rank_slow")
            time.sleep(slow[1])
            now_local = time.monotonic()
        record = {"key": key, "op": op,
                  "arrivals": {process_rank: now_local}}
    else:
        # single-controller SPMD: one host dispatches for every rank.
        # Simulated arrivals are identical but for the chaos charge, so
        # the record is COMPACT — base time + late exceptions — instead
        # of an O(world) dict per dispatch (expanded only at
        # attribution/merge time)
        late = {}
        # input-side lateness: a rank whose latest batch made it wait is
        # marked that much late at the collective — NO extra sleep (the
        # loader's wall time already passed); the simulated arrival just
        # reflects where it went. Real multi-process ranks need none of
        # this: their loader's sleep delays their real dispatch.
        for r, w in waits.items():
            if 0 <= r < max(1, world):
                late[r] = now_local + w
        if slow is not None and 0 <= slow[0] < max(1, world) and slow[1] > 0:
            chaos.record_injection("rank_slow")
            time.sleep(slow[1])
            late[slow[0]] = time.monotonic()
        record = {"key": key, "op": op, "base": now_local,
                  "late": late, "world": max(1, world)}
    with _lock:
        if _ring.maxlen != _window():
            _resize_ring_locked()
        _ring.append(record)
    _emit_arrival_events(op, key, _expand_arrivals(record))
    return key


def _expand_arrivals(record: dict) -> Dict[int, float]:
    """Per-rank arrival map of a ring record (compact single-controller
    records expand to world entries; multi-process records pass
    through)."""
    if "arrivals" in record:
        return dict(record["arrivals"])
    out = {r: record["base"] for r in range(record["world"])}
    out.update(record["late"])
    return out


def _resize_ring_locked() -> None:
    global _ring
    _ring = collections.deque(_ring, maxlen=_window())


#: above this world size, simulated per-rank trace rows collapse to one
#: shared lane + the late ranks (256 identical rows per collective would
#: churn the span ring and be unreadable in Perfetto anyway)
MAX_TRACE_RANK_LANES = 64


def _emit_arrival_events(op: str, key, arrivals: Dict[int, float]) -> None:
    """Mirror the arrivals into the host trace as per-rank rows. Each
    rank's bar runs from its arrival to the LAST arrival — the time it
    (would have) spent waiting for the straggler — so the merged timeline
    shows one collective as an aligned row per rank. Timestamps are
    raw-local (the merge tool applies the clock correction file-wide)."""
    if not _trace.enabled():
        return
    t_last = max(arrivals.values())
    if len(arrivals) > MAX_TRACE_RANK_LANES:
        base_t = min(arrivals.values())
        distinct = {r: t for r, t in arrivals.items() if t != base_t}
        arrivals = dict(distinct)
        arrivals[-1] = base_t  # lane "rank-1": the on-time cohort
    for r, t in arrivals.items():
        ts = _trace.rel_us(t)
        _trace.add_raw(
            {
                "ph": "X",
                "pid": f"{_trace.RANK_PID_PREFIX}{r}",
                "tid": op,
                "name": f"{op} s{key[0]}.{key[2]}",
                "ts": round(ts, 1),
                "dur": round(max(0.0, (t_last - t)) * 1e6, 1),
                "args": {
                    "step": key[0], "gen": key[1], "seq": key[2],
                    "op": op, "rank": r,
                },
            }
        )


def export_recent(n: Optional[int] = None) -> List[dict]:
    """JSON-able copy of the arrival ring (newest last) — what
    :class:`~horovod_tpu.observability.aggregate.MetricsPublisher` ships in
    each snapshot. Keys become lists, ranks become strings (JSON object
    keys), and the CURRENT clock offset is applied here — export time, not
    capture time — so arrivals recorded before the first clock sync are
    corrected retroactively. Compact single-controller records stay
    compact on the wire (base + late exceptions, not world entries)."""
    with _lock:
        records = list(_ring)
    if n is not None:
        records = records[-n:]
    off = _clock.offset()
    out = []
    for rec in records:
        e = {"key": list(rec["key"]), "op": rec["op"]}
        if "arrivals" in rec:
            e["arrivals"] = {
                str(r): t + off for r, t in rec["arrivals"].items()
            }
        else:
            e["base"] = rec["base"] + off
            e["late"] = {str(r): t + off for r, t in rec["late"].items()}
            e["world"] = rec["world"]
        out.append(e)
    return out


def merge_arrival_exports(exports: Iterable[List[dict]]) -> List[dict]:
    """Union per-rank arrival exports by correlation key (the fleet-side
    correlation step): records with the same ``(step, gen, seq)`` from
    different ranks' snapshots fold into one arrival map."""
    merged: Dict[Tuple[int, int, int], dict] = {}
    for export in exports:
        for rec in export or ():
            try:
                key = tuple(int(k) for k in rec["key"])
                if "arrivals" in rec:
                    norm = {"arrivals": {
                        int(r): float(t)
                        for r, t in rec["arrivals"].items()
                    }}
                else:  # compact single-controller record
                    norm = {
                        "base": float(rec["base"]),
                        "world": int(rec["world"]),
                        "late": {
                            int(r): float(t)
                            for r, t in rec["late"].items()
                        },
                    }
                arrivals = _expand_arrivals(norm)
            except (KeyError, TypeError, ValueError):
                continue
            slot = merged.setdefault(
                key, {"key": key, "op": rec.get("op", "?"), "arrivals": {}}
            )
            slot["arrivals"].update(arrivals)
    return [merged[k] for k in sorted(merged)]


def attribute(
    records: Optional[Iterable[dict]] = None,
    *,
    expected_ranks: Optional[int] = None,
    data_waits: Optional[Dict[int, float]] = None,
) -> Optional[dict]:
    """Fold correlated arrival records into straggler metrics + the health
    feed; returns the current attribution or None. Lock-safe — the rank-0
    aggregation loop and the ``/fleet`` HTTP handler threads can race a
    call without double-striking health for one key.

    `records` defaults to this process's own ring (the single-controller
    case); the fleet aggregator passes :func:`merge_arrival_exports`
    output with `expected_ranks` = the live-rank count. A key is only
    FINALIZED (attributed + remembered, so repeated passes never
    double-count) once its arrival set reaches `expected_ranks` (default:
    2, the single-controller case where arrivals are complete at birth):
    a partial set — one rank's snapshot lagging, most likely the
    straggler's own — is deferred to a later pass instead of being scored
    without its decisive arrival. Each finalized key observes
    ``collective_arrival_spread_seconds``; when the spread clears
    ``HOROVOD_STRAGGLER_THRESHOLD`` the last rank is the collective's
    straggler (``straggler_rank`` gauge, ``straggler_collectives``
    counter) and from ``HOROVOD_STRAGGLER_PERSIST`` consecutive
    attributions of the SAME rank onward, EVERY further attribution
    strikes the health machine (SUSPECT). Re-striking per collective —
    the same cadence as stall warnings — matters in a live loop:
    completed steps beat the machine back to HEALTHY, so a one-shot
    strike would make a persistent-but-progressing straggler invisible
    after one step.

    The returned attribution is STICKY: a pass that sees no new records
    (an HTTP ``/fleet`` scrape between publishes) reports the latest one
    instead of flickering to None; a new under-threshold collective — the
    straggler caught up — clears it.

    `data_waits` (``{rank: recent input wait seconds}``; default: this
    process's own :func:`note_data_wait` map, the single-controller case —
    the fleet aggregator passes per-rank waits it pulled from the merged
    snapshots) classifies a named straggler's **cause**: when the rank's
    input wait explains the arrival spread it is ``"input"``-bound (slow
    disk), otherwise ``"compute"``-bound (slow chip) — the distinction the
    health reason and ``hvd_top`` surface."""
    if records is None:
        with _lock:
            raw = list(_ring)
        records = [
            dict(rec, arrivals=_expand_arrivals(rec)) for rec in raw
        ]
    if data_waits is None:
        with _lock:
            data_waits = dict(_data_wait)
    with _attr_lock:
        return _attribute_locked(records, expected_ranks, data_waits)


def _temporal(key: Tuple[int, int, int]) -> Tuple[int, int, int]:
    """Keys in wall-clock order: the elastic generation outranks the step
    (a resize rolls the step back while time moves forward)."""
    return (key[1], key[0], key[2])


def _attribute_locked(records, expected_ranks: Optional[int],
                      data_waits: Optional[Dict[int, float]] = None):
    global _streak_rank, _streak, _current
    need = max(2, expected_ranks or 2)
    current: Optional[dict] = None
    # process in TEMPORAL order (generation outranks step): merged records
    # arrive key-sorted, which puts post-resize (higher-gen, step-rolled-
    # back) keys BEFORE leftover pre-resize ones — an old healthy key
    # processed last would wipe the attribution the newer keys just built
    records = sorted(records, key=lambda r: _temporal(tuple(r["key"])))
    for rec in records:
        key = tuple(rec["key"])
        arrivals = rec["arrivals"]
        if len(arrivals) < need or key in _seen_keys:
            continue
        _seen_keys[key] = True
        while len(_seen_keys) > 4 * _window():
            _seen_keys.popitem(last=False)
        ts = sorted(arrivals.items(), key=lambda kv: kv[1])
        spread = ts[-1][1] - ts[0][1]
        if _metrics.enabled():
            _metrics.histogram(
                "collective_arrival_spread_seconds",
                help="latest minus earliest rank arrival per correlated "
                     "collective",
            ).observe(spread)
        if spread >= threshold():
            rank = int(ts[-1][0])
            # input-vs-compute attribution: the rank's recent input wait
            # explains the spread when it covers at least half of it (and
            # clears the threshold itself) — then the disk, not the chip,
            # is the bottleneck
            wait = float((data_waits or {}).get(rank, 0.0))
            cause = (
                "input"
                if wait >= max(threshold(), 0.5 * spread)
                else "compute"
            )
            current = {
                "rank": rank,
                "spread_seconds": spread,
                "key": list(key),
                "op": rec.get("op", "?"),
                "cause": cause,
            }
            if _metrics.enabled():
                _metrics.gauge(
                    "straggler_rank",
                    help="rank last to arrive at the most recent "
                         "over-threshold collective (-1: none)",
                ).set(rank)
                _metrics.counter(
                    "straggler_collectives",
                    help="correlated collectives attributed to a straggler",
                    rank=rank,
                ).inc()
            if rank == _streak_rank:
                _streak += 1
            else:
                _streak_rank, _streak = rank, 1
            if _streak >= persist_after():
                _health_mod().record_straggler(rank, spread, cause=cause)
        else:
            if _current is not None and _temporal(key) < _temporal(
                tuple(_current["key"])
            ):
                # an OLDER deferred key finalizing late (its last arrival
                # just landed) says nothing about the straggler every
                # NEWER collective is still naming — don't let it clear
                # the streak/attribution out of order
                continue
            _streak_rank, _streak = None, 0
            current = None
            _current = None
            if _metrics.enabled():
                _metrics.gauge(
                    "straggler_rank",
                    help="rank last to arrive at the most recent "
                         "over-threshold collective (-1: none)",
                ).set(-1)
    if current is not None:
        current["streak"] = _streak
        _current = current
    return _current


def reset() -> None:
    """Forget correlation + attribution state (tests / per-run
    isolation)."""
    global _step, _generation, _seq, _last_key, _window_cache
    global _threshold_cache, _persist_cache
    global _streak_rank, _streak, _current
    _threshold_cache = None
    _persist_cache = None
    with _lock:
        _step = 0
        _generation = 0
        _seq = 0
        _last_key = None
        _window_cache = None
        _ring.clear()
        _data_wait.clear()
    with _attr_lock:
        _seen_keys.clear()
        _streak_rank, _streak, _current = None, 0, None
