"""Host-side chrome-trace span recorder — the Python half of the merged
timeline.

The native core (``csrc/``) already writes a chrome://tracing JSON array of
negotiation/launch phases to ``HOROVOD_TIMELINE`` (rank 0, reference
``common/timeline.{h,cc}``). What that file cannot show is where the
*Python* layer spends time: enqueue calls into the core, the execute
callback receiving a fused plan, eager collective dispatch. This module
records those as chrome-trace events and, at shutdown, merges them into the
SAME file the core wrote — one Perfetto load then shows controller + host
activity on a shared monotonic timebase (``set_epoch`` is called right
before ``hvd_core_init`` so both sides' ``ts=0`` coincide to within
microseconds; ``steady_clock`` and ``time.monotonic`` read the same Linux
clock). Load the XLA device trace from :func:`horovod_tpu.profiler.timeline`
alongside it for device activity.

stdlib only; recording is enabled iff ``HOROVOD_TIMELINE`` is set (and
``HOROVOD_TRACE_HOST`` is not 0) — the per-call cost when disabled is one
env-cached bool check returning a shared no-op context manager.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional

__all__ = [
    "enabled",
    "set_epoch",
    "set_recording",
    "span",
    "instant",
    "flush",
    "reset",
    "events",
]

_lock = threading.Lock()
_events: list = []
_epoch_ns: Optional[int] = None
_enabled_cache: Optional[bool] = None
_recording = True  # False on ranks whose buffer would never be flushed
_dropped = 0

#: backstop for a job that never flushes: beyond this many buffered events
#: new ones are counted in ``_dropped`` instead of growing host RAM forever
MAX_BUFFERED_EVENTS = 2_000_000

#: chrome-trace ``pid`` lane for host events. The native writer uses the
#: integer rank as its pid; a distinct string keeps the two process rows
#: separate in Perfetto while living in one file.
HOST_PID = "python-host"


def enabled() -> bool:
    """True iff host tracing is on: ``HOROVOD_TIMELINE`` set,
    ``HOROVOD_TRACE_HOST`` not 0, and this process's buffer will actually
    be flushed (see :func:`set_recording`). The env half is cached after
    the first read (both knobs are fixed at job start, like the
    reference's Timeline)."""
    global _enabled_cache
    if _enabled_cache is None:
        _enabled_cache = bool(os.environ.get("HOROVOD_TIMELINE")) and (
            os.environ.get("HOROVOD_TRACE_HOST", "1").lower()
            not in ("0", "false")
        )
    return _recording and _enabled_cache


def set_recording(on: bool) -> None:
    """Turn span recording on/off for this process. ``horovod_tpu.init``
    disables it on ranks != 0 — only rank 0's buffer is ever flushed
    (coordinator-only, like the native Timeline), so other ranks must not
    pay the append cost or the memory growth for events that would be
    discarded at exit."""
    global _recording
    _recording = bool(on)


def _now_us() -> float:
    global _epoch_ns
    now = time.monotonic_ns()
    if _epoch_ns is None:
        _epoch_ns = now
    return (now - _epoch_ns) / 1e3


def set_epoch() -> None:
    """Pin ts=0 to *now*. ``NativeCore.__init__`` calls this immediately
    before ``hvd_core_init`` so host and native timestamps share an origin;
    without a core, the first recorded event sets the epoch."""
    global _epoch_ns
    _epoch_ns = time.monotonic_ns()


class _Span:
    """Re-entrant-per-instance complete-event recorder ('X' phase)."""

    __slots__ = ("tid", "name", "_t0")

    def __init__(self, tid: str, name: str):
        self.tid = tid
        self.name = name

    def __enter__(self):
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc):
        t1 = _now_us()
        _append(
            {
                "ph": "X",
                "pid": HOST_PID,
                "tid": self.tid,
                "name": self.name,
                "ts": round(self._t0, 1),
                "dur": round(t1 - self._t0, 1),
            }
        )
        return False


def _append(event: dict) -> None:
    global _dropped
    with _lock:
        if len(_events) >= MAX_BUFFERED_EVENTS:
            _dropped += 1
            return
        _events.append(event)


@contextlib.contextmanager
def _noop_span():
    yield None


_NOOP = _noop_span  # factory: cheapest disabled path is one call + yield


def span(tid: str, name: str):
    """Context manager recording one complete event on host lane ``tid``
    (e.g. ``with trace.span("enqueue", tensor_name): ...``)."""
    if not enabled():
        return _NOOP()
    return _Span(tid, name)


def instant(tid: str, name: str) -> None:
    """One instant event (the host analog of the native writer's
    ``CYCLE_START`` markers)."""
    if not enabled():
        return
    _append(
        {
            "ph": "i",
            "s": "t",
            "pid": HOST_PID,
            "tid": tid,
            "name": name,
            "ts": round(_now_us(), 1),
        }
    )


def events() -> list:
    """Copy of the buffered (not yet flushed) host events."""
    with _lock:
        return list(_events)


def reset() -> None:
    """Drop buffered events and the cached enable/epoch/recording state
    (tests)."""
    global _epoch_ns, _enabled_cache, _recording, _dropped
    with _lock:
        _events.clear()
    _epoch_ns = None
    _enabled_cache = None
    _recording = True
    _dropped = 0


def flush(path: Optional[str] = None) -> Optional[str]:
    """Merge buffered host events into the chrome-trace file at ``path``
    (default: ``HOROVOD_TIMELINE``) and clear the buffer.

    Call AFTER the native core shut down (its writer thread closes the JSON
    array then): the existing file is parsed, host events are appended, and
    the merged array is rewritten as valid JSON. With no existing/parseable
    file the host events alone are written. ``horovod_tpu.shutdown`` does
    this on process rank 0 — the rank whose file the core wrote.

    Returns the path written, or None when there was nothing to do.
    """
    global _dropped
    path = path or os.environ.get("HOROVOD_TIMELINE")
    with _lock:
        pending, _events[:] = list(_events), []
        dropped, _dropped = _dropped, 0
    if not path or not pending:
        return None
    if dropped:
        pending.append(
            {
                "ph": "i", "s": "g", "pid": HOST_PID, "tid": "meta",
                "name": f"host-trace buffer full: {dropped} events dropped",
                "ts": round(_now_us(), 1),
            }
        )
    merged: list = []
    if os.path.exists(path):
        try:
            with open(path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            # unparseable: the core is still writing (or foreign content) —
            # never clobber it; park host events in a sidecar instead
            path = path + ".host.json"
        else:
            if isinstance(existing, list):
                merged = existing
    merged.extend(pending)
    tmp = path + ".host.tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, indent=1)
    os.replace(tmp, path)
    return path
