"""Host-side chrome-trace span recorder — the Python half of the merged
timeline.

The native core (``csrc/``) already writes a chrome://tracing JSON array of
negotiation/launch phases to ``HOROVOD_TIMELINE`` (rank 0, reference
``common/timeline.{h,cc}``). What that file cannot show is where the
*Python* layer spends time: enqueue calls into the core, the execute
callback receiving a fused plan, eager collective dispatch. This module
records those as chrome-trace events and, at shutdown, merges them into the
SAME file the core wrote — one Perfetto load then shows controller + host
activity on a shared monotonic timebase (``set_epoch`` is called right
before ``hvd_core_init`` so both sides' ``ts=0`` coincide to within
microseconds; ``steady_clock`` and ``time.monotonic`` read the same Linux
clock). Load the XLA device trace from :func:`horovod_tpu.profiler.timeline`
alongside it for device activity.

Fleet tracing (ISSUE 7): collective spans carry a ``(step, generation,
seq)`` correlation key in their ``args`` (stamped by
:mod:`~horovod_tpu.observability.straggler`), every rank records — ranks
!= 0 flush to a ``<path>.rank<r>.json`` sidecar — and
:func:`horovod_tpu.observability.clock.merge_rank_traces` merges the
per-rank files into one skew-corrected timeline where one collective's
spans align as a row per rank. The span buffer is a capped ring
(``HOROVOD_TRACE_MAX_SPANS``): when full the OLDEST events are dropped (a
long soak keeps its most recent window) and the ``trace_spans_dropped``
counter records the loss.

stdlib only; recording is enabled iff ``HOROVOD_TIMELINE`` is set (and
``HOROVOD_TRACE_HOST`` is not 0) — the per-call cost when disabled is one
env-cached bool check returning a shared no-op context manager.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Optional

from horovod_tpu.observability import metrics as _metrics

__all__ = [
    "enabled",
    "set_epoch",
    "set_recording",
    "set_clock_info",
    "span",
    "instant",
    "add_raw",
    "rel_us",
    "epoch_ns",
    "flush",
    "reset",
    "events",
    "max_spans",
]

_lock = threading.Lock()
_events: "collections.deque" = collections.deque()
_epoch_ns: Optional[int] = None
_enabled_cache: Optional[bool] = None
_recording = True  # False on ranks whose buffer would never be flushed
_dropped = 0
_max_spans_cache: Optional[int] = None
_clock_info: Optional[dict] = None  # rank/offset metadata for merge tools

#: default span-ring capacity — generous (a multi-hour soak's worth of
#: eager dispatches) while still bounding host RAM; override with
#: ``HOROVOD_TRACE_MAX_SPANS``
DEFAULT_MAX_SPANS = 2_000_000

#: chrome-trace ``pid`` lane for host events. The native writer uses the
#: integer rank as its pid; a distinct string keeps the two process rows
#: separate in Perfetto while living in one file.
HOST_PID = "python-host"

#: ``pid`` lane prefix for per-rank correlated collective events (the
#: fleet-view rows): rank r's arrivals land on ``rank<r>``
RANK_PID_PREFIX = "rank"


def enabled() -> bool:
    """True iff host tracing is on: ``HOROVOD_TIMELINE`` set,
    ``HOROVOD_TRACE_HOST`` not 0, and this process's buffer will actually
    be flushed (see :func:`set_recording`). The env half is cached after
    the first read (both knobs are fixed at job start, like the
    reference's Timeline)."""
    global _enabled_cache
    if _enabled_cache is None:
        _enabled_cache = bool(os.environ.get("HOROVOD_TIMELINE")) and (
            os.environ.get("HOROVOD_TRACE_HOST", "1").lower()
            not in ("0", "false")
        )
    return _recording and _enabled_cache


def set_recording(on: bool) -> None:
    """Turn span recording on/off for this process. With fleet tracing
    every rank records (its buffer flushes to a per-rank sidecar at
    shutdown); ``HOROVOD_TRACE_ALL_RANKS=0`` restores the PR-1
    coordinator-only behavior where ``horovod_tpu.init`` disables
    recording on ranks != 0."""
    global _recording
    _recording = bool(on)


def max_spans() -> int:
    """The span-ring capacity (``HOROVOD_TRACE_MAX_SPANS``, default
    :data:`DEFAULT_MAX_SPANS`; ``0`` means unbounded). Cached after first
    read; :func:`reset` re-reads."""
    global _max_spans_cache
    if _max_spans_cache is None:
        try:
            _max_spans_cache = int(
                os.environ.get("HOROVOD_TRACE_MAX_SPANS", "")
                or DEFAULT_MAX_SPANS
            )
        except ValueError:
            _max_spans_cache = DEFAULT_MAX_SPANS
    return _max_spans_cache


def _now_us() -> float:
    global _epoch_ns
    now = time.monotonic_ns()
    if _epoch_ns is None:
        _epoch_ns = now
    return (now - _epoch_ns) / 1e3


def set_epoch() -> None:
    """Pin ts=0 to *now*. ``NativeCore.__init__`` calls this immediately
    before ``hvd_core_init`` so host and native timestamps share an origin;
    without a core, the first recorded event sets the epoch."""
    global _epoch_ns
    _epoch_ns = time.monotonic_ns()


def epoch_ns() -> int:
    """Raw ``time.monotonic_ns`` value of this process's ts=0 origin
    (established on first use). The clock-sync metadata records it so the
    merge tool can place per-rank files on one timebase."""
    _now_us()  # establish the epoch if nothing recorded yet
    return int(_epoch_ns)


def rel_us(monotonic_s: float) -> float:
    """Convert a local ``time.monotonic()`` reading (seconds) into this
    process's trace timebase (µs since the epoch)."""
    _now_us()
    return (monotonic_s * 1e9 - _epoch_ns) / 1e3


def set_clock_info(info: Optional[dict]) -> None:
    """Attach clock-sync metadata (rank, epoch origin, offset to the KV
    server's clock, error bound — see
    :func:`horovod_tpu.observability.clock.refresh`) that :func:`flush`
    embeds as a ``clock_sync`` meta event, making the file mergeable on a
    skew-corrected timebase."""
    global _clock_info
    _clock_info = dict(info) if info else None


class _Span:
    """Re-entrant-per-instance complete-event recorder ('X' phase)."""

    __slots__ = ("tid", "name", "args", "_t0")

    def __init__(self, tid: str, name: str, args: Optional[dict] = None):
        self.tid = tid
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc):
        t1 = _now_us()
        ev = {
            "ph": "X",
            "pid": HOST_PID,
            "tid": self.tid,
            "name": self.name,
            "ts": round(self._t0, 1),
            "dur": round(t1 - self._t0, 1),
        }
        if self.args:
            ev["args"] = self.args
        _append(ev)
        return False


def _append(event: dict) -> None:
    global _dropped
    overflowed = False
    with _lock:
        cap = max_spans()
        while cap > 0 and len(_events) >= cap:
            # ring semantics: drop the OLDEST so a long soak keeps its most
            # recent window (the reverse — refusing new events — would
            # freeze the trace at the start of the run, the least useful
            # window for debugging what eventually went wrong)
            _events.popleft()
            _dropped += 1
            overflowed = True
        _events.append(event)
    if overflowed and _metrics.enabled():
        _metrics.counter(
            "trace_spans_dropped",
            help="host-trace events evicted by the span ring "
                 "(HOROVOD_TRACE_MAX_SPANS)",
        ).inc()


def add_raw(event: dict) -> None:
    """Append one pre-built chrome-trace event (the straggler layer's
    per-rank arrival rows use this to write onto ``rank<r>`` pid lanes).
    No-op while recording is disabled."""
    if not enabled():
        return
    _append(event)


@contextlib.contextmanager
def _noop_span():
    yield None


_NOOP = _noop_span  # factory: cheapest disabled path is one call + yield


def span(tid: str, name: str, **args):
    """Context manager recording one complete event on host lane ``tid``
    (e.g. ``with trace.span("enqueue", tensor_name): ...``). Keyword
    arguments land in the event's ``args`` — collective spans carry their
    ``(step, gen, seq)`` correlation key this way."""
    if not enabled():
        return _NOOP()
    return _Span(tid, name, args or None)


def instant(tid: str, name: str, **args) -> None:
    """One instant event (the host analog of the native writer's
    ``CYCLE_START`` markers)."""
    if not enabled():
        return
    ev = {
        "ph": "i",
        "s": "t",
        "pid": HOST_PID,
        "tid": tid,
        "name": name,
        "ts": round(_now_us(), 1),
    }
    if args:
        ev["args"] = args
    _append(ev)


def events() -> list:
    """Copy of the buffered (not yet flushed) host events."""
    with _lock:
        return list(_events)


def dropped() -> int:
    """Events evicted from the ring since the last flush/reset."""
    return _dropped


def reset() -> None:
    """Drop buffered events and the cached enable/epoch/recording state
    (tests)."""
    global _epoch_ns, _enabled_cache, _recording, _dropped
    global _max_spans_cache, _clock_info
    with _lock:
        _events.clear()
    _epoch_ns = None
    _enabled_cache = None
    _recording = True
    _dropped = 0
    _max_spans_cache = None
    _clock_info = None


def flush(path: Optional[str] = None) -> Optional[str]:
    """Merge buffered host events into the chrome-trace file at ``path``
    (default: ``HOROVOD_TIMELINE``) and clear the buffer.

    Call AFTER the native core shut down (its writer thread closes the JSON
    array then): the existing file is parsed, host events are appended, and
    the merged array is rewritten as valid JSON. With no existing/parseable
    file the host events alone are written. ``horovod_tpu.shutdown`` does
    this on process rank 0 — the rank whose file the core wrote — and
    writes ranks != 0 to a ``<HOROVOD_TIMELINE>.rank<r>.json`` sidecar
    each (merge them with
    :func:`horovod_tpu.observability.clock.merge_rank_traces`).

    Returns the path written, or None when there was nothing to do.
    """
    global _dropped
    path = path or os.environ.get("HOROVOD_TIMELINE")
    with _lock:
        pending = list(_events)
        _events.clear()
        dropped_n, _dropped = _dropped, 0
    if not path or not pending:
        return None
    if dropped_n:
        pending.append(
            {
                "ph": "i", "s": "g", "pid": HOST_PID, "tid": "meta",
                "name": f"host-trace ring full: {dropped_n} oldest events "
                        "dropped",
                "ts": round(_now_us(), 1),
            }
        )
    if _clock_info:
        # merge tools read this to shift the file onto the fleet timebase
        pending.append(
            {
                "ph": "i", "s": "g", "pid": HOST_PID, "tid": "meta",
                "name": "clock_sync", "ts": 0.0,
                "args": dict(_clock_info),
            }
        )
    merged: list = []
    if os.path.exists(path):
        try:
            with open(path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            # unparseable: the core is still writing (or foreign content) —
            # never clobber it; park host events in a sidecar instead
            path = path + ".host.json"
        else:
            if isinstance(existing, list):
                merged = existing
    merged.extend(pending)
    tmp = path + ".host.tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, indent=1)
    os.replace(tmp, path)
    return path
