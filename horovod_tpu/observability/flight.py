"""Black-box flight recorder + cross-rank hang forensics.

The timeline/stall machinery (PR 1/7) can say *that* a collective stalled
— while the process is alive to be asked. What it cannot answer is *why a
job died or hung after the fact*: a wedged rank, a SIGKILL, or a mesh
deadlocked on a divergent schedule takes the metrics registry, the trace
ring, and the sanitizer records down with it. Production collective stacks
solved this with an always-on crash-safe event ring (PyTorch's NCCL
"flight recorder" / ``TORCH_NCCL_TRACE_BUFFER``); this module is that
instrument for the TPU-native stack:

- **Flight ring** — a bounded in-process ring of structured events,
  appended through the hooks that already exist: collective begin/end with
  the sanitizer's ``(step, generation, seq)`` correlation signature
  (``ops.collective._record_eager_op`` / the ``_guarded`` launch wrapper),
  step boundaries (``InstrumentedStep``), health-machine transitions,
  chaos injections, elastic membership epochs, per-step sanitizer schedule
  hashes, serving publish/subscribe/admission decisions, and input-plane
  ``data`` events (prefetch-watchdog stalls, shard quarantines — rare and
  crash-adjacent, so they flush to the sidecar immediately like health
  transitions; ``docs/data.md``). Always on (``HOROVOD_FLIGHT=0`` opts
  out); the per-event cost is one dict append under a lock.
- **Crash-durable sidecar** — with ``HOROVOD_FLIGHT_DIR`` set, events are
  batch-appended to a per-rank JSONL sidecar
  (``flight-rank<r>.jsonl``), torn-tail tolerant like the rendezvous WAL
  (a line cut mid-write by SIGKILL is skipped at load; everything before
  it is good). Non-collective events flush immediately; the hot
  collective stream flushes every ``HOROVOD_FLIGHT_FLUSH_EVERY`` events.
  The file is compacted back to the ring contents when it outgrows
  ``HOROVOD_FLIGHT_MAX_BYTES``, so the record stays bounded AND survives
  SIGKILL.
- **Hang detector** — a watchdog (armed when ``HOROVOD_HANG_TIMEOUT`` > 0)
  that fires when no collective-end/step progress lands for the timeout:
  it pushes every reachable rank's ring tail to the rendezvous KV
  (``/flight/tail/<rank>``, beside the ``/sanitize`` records) and, on
  rank 0, produces a merged clock-skew-corrected diagnosis
  (:func:`analyze`) naming the collective ``(step, gen, seq)`` the stuck
  ranks are parked on and the rank(s) that never arrived — distinguishing
  "rank N missing at seq K" from "schedules diverged at seq K" by
  cross-checking the per-step sanitizer hashes. The verdict feeds
  :func:`horovod_tpu.resilience.health.record_hang` and (with
  ``HOROVOD_HANG_EVICT=1``) queues the missing rank for elastic eviction
  at the next membership sweep.
- **Offline forensics** — ``tools/hvd_blackbox.py`` replays the same
  :func:`analyze` from sidecar files alone (merge, skew-correct, unified
  timeline + verdict) for the case where every process is already dead.

Topology note (the same convention as the sanitizer/straggler layers):
single-controller SPMD dispatches on behalf of every rank, so the one
sidecar carries a ``ranks`` list in its header. The deterministic chaos
charge ``HOROVOD_CHAOS=rank_hang_at_step=K`` makes the loop testable on
the 8-device CPU mesh: the highest rank (never rank 0) "stops dispatching"
mid-step — its view of the record is frozen *before* the parked collective
and written to its own sidecar, every survivor records the begin with no
end, and the dispatching thread really holds (released by the live
diagnosis or after ``rank_hang_hold`` seconds) so the watchdog fires for
real. Multi-process: the highest process rank holds *before* dispatching,
parking its peers inside the actual collective.

Clock model: events are stamped with raw local ``time.monotonic``; the
sidecar header and every KV tail carry this rank's offset to the KV
server's clock (:mod:`horovod_tpu.observability.clock`), applied at
merge/analysis time — records captured before the first clock sync are
corrected retroactively, the same discipline as the straggler ring.

stdlib-only at import (chaos/health/sanitizer/basics are imported lazily
at call time); importing this module must never initialize a device
backend.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from horovod_tpu.observability import clock as _clock
from horovod_tpu.observability import metrics as _metrics

logger = logging.getLogger("horovod_tpu.observability")

__all__ = [
    "FLIGHT_ENV",
    "DIR_ENV",
    "enabled",
    "configure",
    "reset",
    "record",
    "collective_begin",
    "collective_end",
    "step_boundary",
    "events",
    "flush",
    "sidecar_path",
    "load_sidecar",
    "load_dir",
    "analyze",
    "analyze_loaded",
    "analyze_dir",
    "push_tails",
    "read_tails",
    "maybe_arm_watchdog",
    "arm_watchdog",
    "disarm_watchdog",
    "last_hang",
    "take_hung_ranks",
    "evict_enabled",
    "hang_timeout",
    "TAIL_SCOPE",
]

FLIGHT_ENV = "HOROVOD_FLIGHT"
DIR_ENV = "HOROVOD_FLIGHT_DIR"
MAX_EVENTS_ENV = "HOROVOD_FLIGHT_MAX_EVENTS"
FLUSH_EVERY_ENV = "HOROVOD_FLIGHT_FLUSH_EVERY"
MAX_BYTES_ENV = "HOROVOD_FLIGHT_MAX_BYTES"
HANG_TIMEOUT_ENV = "HOROVOD_HANG_TIMEOUT"
HANG_TAIL_ENV = "HOROVOD_HANG_TAIL"
HANG_EVICT_ENV = "HOROVOD_HANG_EVICT"

#: KV namespace the watchdog pushes ring tails under (``<scope>/<rank>``),
#: beside the sanitizer's ``/sanitize`` records
TAIL_SCOPE = "/flight/tail"

#: ring capacity default — a few thousand recent events is hours of step
#: boundaries or minutes of dense eager dispatch, at ~100 B each
DEFAULT_MAX_EVENTS = 4096
DEFAULT_FLUSH_EVERY = 32
DEFAULT_MAX_BYTES = 8 << 20
DEFAULT_HANG_TAIL = 64

# re-entrant: the watchdog thread's firing path re-enters through
# flush()/record() while helpers consult the env caches under the lock
_lock = threading.RLock()
_events: "collections.deque" = collections.deque()
_pending: List[dict] = []  # events awaiting a sidecar append (dir set only)
_enabled_cache: Optional[bool] = None
_dir_override: Optional[str] = None
_max_events_cache: Optional[int] = None
_flush_every_cache: Optional[int] = None

_sidecar_file = None
_sidecar_path_current: Optional[str] = None
_sidecar_bytes = 0
_header_sig: Optional[tuple] = None

_kv = None  # KVStoreServer/KVStoreClient duck-type, or the local store
_world_override: Optional[int] = None
_rank_override: Optional[int] = None

# correlation state for collective_end (once-per-key)
_last_begin: Optional[Tuple[Tuple[int, int, int], str]] = None
_last_end_key: Optional[Tuple[int, int, int]] = None

# single-controller rank-hang simulation: the victim's view of the record
# is frozen at the moment it "stopped dispatching"
_frozen_rank: Optional[int] = None
_frozen_tail: Optional[List[dict]] = None

# hang-detector state: (thread, its OWN stop event) — per-thread, so a
# re-arm can never resurrect a predecessor blocked in a slow firing (a
# shared event cleared by arm_watchdog would)
_watchdog: Optional[Tuple[threading.Thread, threading.Event]] = None
_release = threading.Event()  # set by a live diagnosis; ends a chaos hold
_last_progress: Optional[float] = None
_armed_at: Optional[float] = None
_fired_at: Optional[float] = None
_last_hang: Optional[dict] = None
_hung_ranks: List[int] = []


# --------------------------------------------------------------------- config


def enabled() -> bool:
    """True unless ``HOROVOD_FLIGHT=0``: the recorder is always-on (the
    ring is the whole point — the record must exist *before* anything goes
    wrong). Env cached after first read; :func:`reset` re-reads."""
    global _enabled_cache
    if _enabled_cache is None:
        with _lock:
            _enabled_cache = os.environ.get(
                FLIGHT_ENV, "1"
            ).lower() not in ("0", "false", "off")
    return _enabled_cache


def flight_dir() -> Optional[str]:
    """Sidecar directory (``HOROVOD_FLIGHT_DIR`` or :func:`configure`
    override); None = in-memory ring only (no crash durability)."""
    if _dir_override is not None:
        return _dir_override or None
    return os.environ.get(DIR_ENV) or None


def max_events() -> int:
    global _max_events_cache
    if _max_events_cache is None:
        with _lock:
            try:
                _max_events_cache = int(
                    os.environ.get(MAX_EVENTS_ENV, "")
                    or DEFAULT_MAX_EVENTS
                )
            except ValueError:
                _max_events_cache = DEFAULT_MAX_EVENTS
    return _max_events_cache


def _flush_every() -> int:
    global _flush_every_cache
    if _flush_every_cache is None:
        with _lock:
            try:
                _flush_every_cache = max(1, int(
                    os.environ.get(FLUSH_EVERY_ENV, "")
                    or DEFAULT_FLUSH_EVERY
                ))
            except ValueError:
                _flush_every_cache = DEFAULT_FLUSH_EVERY
    return _flush_every_cache


def _max_bytes() -> int:
    try:
        return int(os.environ.get(MAX_BYTES_ENV, "") or DEFAULT_MAX_BYTES)
    except ValueError:
        return DEFAULT_MAX_BYTES


def hang_timeout() -> float:
    """``HOROVOD_HANG_TIMEOUT`` in seconds; 0 (the default) leaves the
    watchdog unarmed."""
    try:
        return float(os.environ.get(HANG_TIMEOUT_ENV, "0") or 0.0)
    except ValueError:
        return 0.0


def _hang_tail() -> int:
    try:
        return max(8, int(
            os.environ.get(HANG_TAIL_ENV, "") or DEFAULT_HANG_TAIL
        ))
    except ValueError:
        return DEFAULT_HANG_TAIL


def evict_enabled() -> bool:
    """``HOROVOD_HANG_EVICT=1``: a diagnosed missing rank is queued for
    elastic eviction at the next membership sweep."""
    return os.environ.get(HANG_EVICT_ENV, "0").lower() in ("1", "true", "on")


def configure(*, on: Optional[bool] = None, dir: Optional[str] = None,
              kv=None, world: Optional[int] = None,
              rank: Optional[int] = None) -> None:
    """Programmatic setup (tests / explicit wiring): flip the switch, point
    the sidecar at a directory (``dir=""`` disables the sidecar regardless
    of the env), wire a KV store for tail pushes, or pin the world size /
    this process's rank (a recorder used outside an initialized data
    plane — drills, tools — has no ``basics`` identity to ask)."""
    global _enabled_cache, _dir_override, _kv, _world_override
    global _rank_override
    with _lock:
        if on is not None:
            _enabled_cache = bool(on)
        if dir is not None:
            _dir_override = dir
            _close_sidecar_locked()
        if kv is not None:
            _kv = kv
        if world is not None:
            _world_override = int(world)
        if rank is not None:
            _rank_override = int(rank)


def reset() -> None:
    """Back to env-driven config and an empty ring (tests)."""
    global _enabled_cache, _dir_override, _max_events_cache
    global _flush_every_cache, _kv, _world_override, _rank_override
    global _last_begin, _last_end_key, _frozen_rank, _frozen_tail
    global _last_progress, _armed_at, _fired_at, _last_hang, _hung_ranks
    disarm_watchdog()
    with _lock:
        _events.clear()
        _pending.clear()
        _close_sidecar_locked()
        _enabled_cache = None
        _dir_override = None
        _max_events_cache = None
        _flush_every_cache = None
        _kv = None  # a fresh in-process store is built on next use
        _world_override = None
        _rank_override = None
        _last_begin = None
        _last_end_key = None
        _frozen_rank = None
        _frozen_tail = None
        _last_progress = None
        _armed_at = None
        _fired_at = None
        _last_hang = None
        _hung_ranks = []
    _release.set()  # free any chaos hold a failed test left parked


def _identity() -> Tuple[int, int, int]:
    """(world, process_rank, process_size) — lazily, like the sanitizer,
    so this module never imports the data plane at import time. The
    :func:`configure` rank/world overrides win (a drill or tool process
    has no initialized data plane to ask; with a pinned rank the process
    is treated as one of ``world`` peers)."""
    world, prank, psize = 1, 0, 1
    try:
        from horovod_tpu import basics

        if basics.is_initialized():
            world, prank, psize = basics.size(), basics.process_rank(), \
                basics.process_size()
    except Exception as e:
        logger.debug("flight identity probe failed: %s", e)
    if _world_override is not None:
        world = _world_override
    if _rank_override is not None:
        prank = _rank_override
        psize = max(psize, _world_override or (prank + 1), prank + 1)
    return world, prank, psize


def _store():
    """The KV the tails ride: an explicit :func:`configure` store, else a
    client from the launcher env, else a fresh in-process stand-in (the
    shared :mod:`~horovod_tpu.run.rendezvous` wiring — lazily imported so
    this module stays import-light)."""
    global _kv
    if _kv is None:
        with _lock:
            if _kv is None:
                from horovod_tpu.run.rendezvous import (
                    InProcessKVStore, kv_client_from_env,
                )

                _kv = kv_client_from_env() or InProcessKVStore()
    return _kv


# ------------------------------------------------------------------ recording


def record(kind: str, /, **fields) -> Optional[dict]:
    """Append one structured event to the ring (and the sidecar batch).
    The timestamp is raw local monotonic seconds; skew correction happens
    at merge/analysis time. ``t``/``kind`` are the record's own keys —
    caller fields must not reuse them (raises, so a clobbered schema can
    never reach the sidecar silently). Returns the event, or None while
    disabled."""
    if not enabled():
        return None
    if "t" in fields or "kind" in fields:
        raise ValueError(
            "flight.record: 't' and 'kind' are reserved event keys"
        )
    ev = {"t": round(time.monotonic(), 6), "kind": str(kind)}
    ev.update(fields)
    _append(ev)
    return ev


def _append(ev: dict) -> None:
    flush_now = False
    with _lock:
        cap = max_events()
        while cap > 0 and len(_events) >= cap:
            _events.popleft()
        _events.append(ev)
        if flight_dir():
            _pending.append(ev)
            # a sidecar that keeps failing to flush (full disk, perms)
            # must not grow _pending forever: keep at most a ring's worth
            # — the same bound, and the tail is what forensics needs
            if cap > 0 and len(_pending) > cap:
                del _pending[: len(_pending) - cap]
            # collective AND serving streams are hot paths — batch them;
            # everything else (health, hang, step, epoch, chaos) is rare
            # and crash-adjacent, so it reaches the OS immediately
            flush_now = (
                ev["kind"] not in ("collective", "serve")
                or len(_pending) >= _flush_every()
            )
    if _metrics.enabled():
        _metrics.counter(
            "flight_events",
            help="structured events appended to the flight ring",
            kind=ev["kind"],
        ).inc()
    if flush_now:
        flush()


def collective_begin(op: str, key: Tuple[int, int, int], *,
                     world: int = 1, process_rank: int = 0,
                     process_size: int = 1) -> None:
    """One eager collective is about to dispatch (called from
    ``ops.collective._record_eager_op`` with the straggler layer's
    correlation key). Applies any armed ``rank_hang_at_step`` chaos charge:
    the multi-process victim holds HERE — before its begin is recorded, so
    its record shows it never arrived — while the single-controller charge
    freezes the victim's view first, records the survivors' begin, then
    holds the dispatching thread."""
    if not enabled():
        return
    global _last_begin
    mode = _maybe_hang(op, key, world, process_rank, process_size)
    with _lock:
        _last_begin = (tuple(key), str(op))
    record(
        "collective", ph="b", op=str(op),
        step=int(key[0]), gen=int(key[1]), seq=int(key[2]),
    )
    if mode == "hold":
        _hold()


def collective_end() -> None:
    """The most recent begin's launch returned (called from the
    ``_guarded`` eager-launch wrapper). Recorded once per correlation key
    — a begin that never gets its end is exactly the parked state the
    hang diagnosis keys on. Dispatch is asynchronous, so "end" means the
    launch was handed to the runtime, not that the collective completed
    on-device; for hang forensics that is the right boundary (a rank that
    reached it made host progress)."""
    if not enabled():
        return
    global _last_end_key
    with _lock:
        if _last_begin is None or _last_begin[0] == _last_end_key:
            return
        key, op = _last_begin
        _last_end_key = key
    record(
        "collective", ph="e", op=op,
        step=key[0], gen=key[1], seq=key[2],
    )
    _note_progress()


def step_boundary(step: int) -> None:
    """A train-step boundary (``InstrumentedStep`` calls this beside the
    straggler/sanitizer scopes). Counts as forward progress."""
    if not enabled():
        return
    record("step", step=int(step))
    _note_progress()


def _note_progress() -> None:
    global _last_progress
    _last_progress = time.monotonic()


# --------------------------------------------------------------- chaos: hang


def _maybe_hang(op, key, world, prank, psize) -> Optional[str]:
    """Apply an armed ``rank_hang_at_step`` charge at this dispatch.
    Fires mid-step (from the step's second collective on) so the record
    shows partial-step progress — the forensically hard case. Returns
    "hold" when the caller should hold AFTER recording the begin
    (single-controller survivors park on the collective); the
    multi-process victim holds here and then resumes (None)."""
    from horovod_tpu.resilience import chaos

    if not chaos.enabled():
        return None
    at = chaos.rank_hang_step()
    if at is None or int(key[0]) < at or int(key[2]) < 1:
        return None
    if psize > 1:
        victim = psize - 1
        if prank != victim:
            # the charge is consumed only by the process that hangs (the
            # grad_corrupt convention): peers park inside the real
            # collective below the victim's held dispatch
            return None
        chaos.consume_rank_hang()
        logger.warning(
            "chaos: rank %d stops dispatching at collective %s (step %d)",
            victim, tuple(key), key[0],
        )
        _hold()
        return None
    victim = world - 1
    if world < 2:
        return None  # nobody to hang relative to
    chaos.consume_rank_hang()
    with _lock:
        _freeze_rank_locked(victim)
    logger.warning(
        "chaos: rank %d stops dispatching at collective %s (step %d); "
        "simulated on the single-controller dispatcher", victim,
        tuple(key), key[0],
    )
    return "hold"


def _hold() -> None:
    """Really stop dispatching: park until the live diagnosis releases us
    or the chaos hold budget expires — bounded, so a drill can never wedge
    tier-1."""
    from horovod_tpu.resilience import chaos

    _release.clear()
    budget = chaos.rank_hang_hold()
    released = _release.wait(max(0.0, budget))
    record("hang", ph="resume", released=bool(released))


def _freeze_rank_locked(victim: int) -> None:
    """Single-controller: pin the victim's view of the record to this
    instant (it 'never arrives' at the collective about to be recorded)
    and write it to the victim's own sidecar; the shared sidecar gets a
    fresh header excluding the victim so offline analysis sees two
    diverged streams."""
    global _frozen_rank, _frozen_tail, _header_sig
    _frozen_rank = int(victim)
    _frozen_tail = list(_events)
    d = flight_dir()
    if d:
        world, prank, psize = _identity()
        path = os.path.join(d, f"flight-rank{victim}.jsonl")
        try:
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(json.dumps(_header(
                    ranks=[int(victim)],
                    world=_domain_world(world, psize))) + "\n")
                for ev in _frozen_tail:
                    f.write(json.dumps(ev, separators=(",", ":")) + "\n")
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("flight freeze sidecar write failed: %s", e)
    _header_sig = None  # next flush re-headers the shared file


# -------------------------------------------------------------------- sidecar


def _domain_world(world: int, psize: int) -> int:
    """The rank domain a diagnosis reasons over. Multi-process, sidecars
    and KV tails are per-PROCESS, so the domain is the process count —
    recording the chip world there would make offline analysis of any
    multi-chip-per-process run name the never-existing sidecar ranks
    missing. Single-controller, the one process simulates every chip
    rank, so the domain is the world."""
    return psize if psize > 1 else max(1, world)


def _header(*, ranks: List[int], world: int) -> dict:
    info = _clock.info()
    return {
        "kind": "header",
        "ranks": ranks,
        "world": int(world),
        "offset_s": float(info.get("offset_s") or 0.0),
        "error_s": info.get("error_s"),
        "generation": int(info.get("generation") or 0),
        "written_t": round(time.monotonic(), 6),
    }


def sidecar_path() -> Optional[str]:
    """This process's sidecar file path (None when the sidecar is off)."""
    d = flight_dir()
    if not d:
        return None
    _world, prank, _psize = _identity()
    return os.path.join(d, f"flight-rank{prank}.jsonl")


def _close_sidecar_locked() -> None:
    global _sidecar_file, _sidecar_path_current, _sidecar_bytes, _header_sig
    if _sidecar_file is not None:
        try:
            _sidecar_file.close()
        except OSError as e:
            logger.debug("flight sidecar close failed: %s", e)
    _sidecar_file = None
    _sidecar_path_current = None
    _sidecar_bytes = 0
    _header_sig = None


def flush() -> Optional[str]:
    """Append pending events to the sidecar and sync them to the OS
    (surviving SIGKILL from there). Opens the file and (re-)writes a
    header whenever the rank set or clock estimate changed; compacts the
    file back to the current ring once it outgrows
    ``HOROVOD_FLIGHT_MAX_BYTES``. No-op without ``HOROVOD_FLIGHT_DIR``.
    Returns the sidecar path, or None."""
    global _sidecar_file, _sidecar_path_current, _sidecar_bytes, _header_sig
    with _lock:
        d = flight_dir()
        if not d:
            _pending.clear()
            return None
        world, prank, psize = _identity()
        path = os.path.join(d, f"flight-rank{prank}.jsonl")
        try:
            if _sidecar_path_current != path:
                _close_sidecar_locked()
                os.makedirs(d, exist_ok=True)
                _sidecar_file = open(path, "a")
                _sidecar_path_current = path
                _sidecar_bytes = (
                    os.path.getsize(path) if os.path.exists(path) else 0
                )
            if psize > 1:
                ranks = [prank]
            else:
                ranks = [
                    r for r in range(max(1, world)) if r != _frozen_rank
                ]
            dom = _domain_world(world, psize)
            info = _clock.info()
            sig = (tuple(ranks), round(float(info.get("offset_s") or 0.0), 9),
                   info.get("generation"))
            if sig != _header_sig:
                line = json.dumps(_header(ranks=ranks, world=dom)) + "\n"
                _sidecar_file.write(line)
                _sidecar_bytes += len(line)
                _header_sig = sig
            for ev in _pending:
                line = json.dumps(ev, separators=(",", ":")) + "\n"
                _sidecar_file.write(line)
                _sidecar_bytes += len(line)
            # the batch is only dropped once it reached the OS: an
            # ENOSPC raised by flush() keeps _pending (bounded by the
            # ring cap in _append) for retry — a silent gap exactly
            # around a disk-pressure incident is what a post-mortem
            # would be investigating. A partially-buffered batch may
            # duplicate on retry after reopen; duplicates are benign
            # where gaps are not.
            _sidecar_file.flush()
            _pending.clear()
            if _metrics.enabled():
                _metrics.counter(
                    "flight_sidecar_flushes",
                    help="flight-ring batches appended to the crash "
                         "sidecar",
                ).inc()
            if _max_bytes() > 0 and _sidecar_bytes > _max_bytes():
                _compact_locked(path, ranks, dom)
        except OSError as e:
            logger.warning("flight sidecar flush failed: %s", e)
            _close_sidecar_locked()
            return None
        return path


def _compact_locked(path: str, ranks: List[int], world: int) -> None:
    """Rewrite the sidecar as header + the current ring (tmp + atomic
    rename, so a crash mid-compaction keeps the old file)."""
    global _sidecar_file, _sidecar_bytes
    tmp = path + ".compact"
    with open(tmp, "w") as f:
        f.write(json.dumps(_header(ranks=ranks, world=world)) + "\n")
        for ev in _events:
            f.write(json.dumps(ev, separators=(",", ":")) + "\n")
    os.replace(tmp, path)
    try:
        _sidecar_file.close()
    except OSError as e:
        logger.debug("flight sidecar close during compaction failed: %s", e)
    _sidecar_file = open(path, "a")
    _sidecar_bytes = os.path.getsize(path)
    if _metrics.enabled():
        _metrics.counter(
            "flight_sidecar_compactions",
            help="sidecar rewrites after outgrowing "
                 "HOROVOD_FLIGHT_MAX_BYTES",
        ).inc()


def events() -> List[dict]:
    """Copy of the in-memory ring (newest last)."""
    with _lock:
        return list(_events)


def _tail_events(n: int, *, rank: Optional[int] = None) -> List[dict]:
    with _lock:
        if rank is not None and rank == _frozen_rank and \
                _frozen_tail is not None:
            return list(_frozen_tail[-n:])
        return list(_events)[-n:]


# ------------------------------------------------------------------ KV tails


def push_tails(kv=None, *, ttl: float = 120.0) -> int:
    """Push ring tails to the KV under ``/flight/tail/<rank>`` so a live
    diagnosis can see every reachable rank's last events. Multi-process:
    this process's own rank only; single-controller: one tail per
    simulated rank (the frozen victim's is its truncated view). `ttl` is
    the tail's KV lease — the firing path scales it past its own
    diagnosis wait. Returns the number of tails pushed."""
    store = kv or _store()
    world, prank, psize = _identity()
    n = _hang_tail()
    info = _clock.info()
    if psize > 1:
        items = {prank: _tail_events(n)}
    else:
        items = {
            r: _tail_events(n, rank=r) for r in range(max(1, world))
        }
    for r, evs in items.items():
        payload = {
            "rank": int(r),
            "world": _domain_world(world, psize),
            "offset_s": float(info.get("offset_s") or 0.0),
            "generation": int(info.get("generation") or 0),
            "pushed_t": round(time.monotonic(), 6),
            "events": evs,
        }
        store.put(
            f"{TAIL_SCOPE}/{r}",
            json.dumps(payload, separators=(",", ":")).encode(),
            ttl=float(ttl),
        )
    if _metrics.enabled():
        _metrics.counter(
            "flight_tail_pushes",
            help="per-rank flight-ring tails pushed to the KV by the "
                 "hang watchdog",
        ).inc(len(items))
    return len(items)


def read_tails(ranks: Iterable[int], kv=None) -> Dict[int, dict]:
    """Read pushed tails for `ranks` from the KV; absent/unreadable ranks
    are simply missing from the result (their absence is itself
    evidence)."""
    store = kv or _store()
    out: Dict[int, dict] = {}
    for r in ranks:
        try:
            blob = store.get(f"{TAIL_SCOPE}/{int(r)}")
        except Exception as e:
            logger.debug("flight tail read for rank %s failed: %s", r, e)
            continue
        if blob is None:
            continue
        try:
            out[int(r)] = json.loads(blob)
        except ValueError:
            continue
    return out


# ------------------------------------------------------------------- analysis


def _temporal(step: int, gen: int, seq: int) -> Tuple[int, int, int]:
    """Keys in wall-clock order: generation outranks step outranks seq
    (the straggler layer's convention — a resize rolls the step back)."""
    return (gen, step, seq)


def analyze(rank_events: Dict[int, Sequence[dict]], *,
            expected: Optional[Iterable[int]] = None) -> dict:
    """The shared hang diagnosis: fold per-rank event streams into a
    verdict. Used identically by the live watchdog (KV tails) and the
    offline ``hvd_blackbox`` tool (sidecar files) so the two can never
    disagree about the same evidence.

    Returns a dict with ``verdict`` one of:

    - ``"rank_missing"`` — ranks parked at collective ``key`` that some
      rank(s) (``hung_ranks``) never began;
    - ``"schedule_divergence"`` — the stuck step's per-rank sanitizer
      hashes (or the ops recorded at the frontier seq) disagree:
      ``hung_ranks`` names the rank(s) whose record differs from rank 0's;
    - ``"all_parked"`` — every expected rank began the frontier collective
      and none finished it (an external stall: device wedge, network);
    - ``"progressing"`` — the frontier collective completed somewhere and
      nobody is parked behind it;
    - ``"no_data"`` — no collective events to reason about.

    ``key`` is the frontier ``[step, gen, seq]``, ``op`` its collective,
    ``waiting`` the parked ranks, ``last_key`` each rank's newest begun
    signature."""
    expected = sorted(expected) if expected is not None else \
        sorted(rank_events)
    per: Dict[int, dict] = {}
    op_at: Dict[Tuple[int, int, int], str] = {}
    all_begun: set = set()
    for r in expected:
        evs = rank_events.get(r) or []
        last_b: Optional[Tuple[int, int, int]] = None
        last_op: Optional[str] = None
        begun_keys = set()
        ended = set()
        scheds: Dict[int, str] = {}
        for ev in evs:
            kind = ev.get("kind")
            if kind == "collective":
                try:
                    tkey = _temporal(
                        int(ev.get("step", 0)), int(ev.get("gen", 0)),
                        int(ev.get("seq", 0)))
                except (TypeError, ValueError):
                    continue
                if ev.get("ph") == "e":
                    ended.add(tkey)
                else:
                    begun_keys.add(tkey)
                    op_at.setdefault(tkey, str(ev.get("op", "?")))
                    if last_b is None or tkey >= last_b:
                        last_b = tkey
                        last_op = ev.get("op")
            elif kind == "sched":
                try:
                    scheds[int(ev.get("step", -1))] = str(ev.get("hash"))
                except (TypeError, ValueError):
                    continue
        all_begun |= begun_keys
        per[r] = {"last_b": last_b, "op": last_op, "ended": ended,
                  "scheds": scheds}
    begun = {r: p["last_b"] for r, p in per.items()
             if p["last_b"] is not None}
    out: dict = {
        "ranks": expected,
        "last_key": {
            str(r): (
                None if per[r]["last_b"] is None
                else [per[r]["last_b"][1], per[r]["last_b"][0],
                      per[r]["last_b"][2]]
            )
            for r in expected
        },
    }
    if not begun:
        out["verdict"] = "no_data"
        return out
    frontier = max(begun.values())
    arrived = sorted(r for r, k in begun.items() if k == frontier)
    waiting = sorted(
        r for r in arrived if frontier not in per[r]["ended"]
    )
    missing = sorted(r for r in expected if r not in arrived)
    ops = {per[r]["op"] for r in arrived if per[r]["op"] is not None}
    out["key"] = [frontier[1], frontier[0], frontier[2]]
    out["op"] = sorted(ops)[0] if ops else "?"
    out["waiting"] = waiting
    # sanitizer cross-check: compare per-step schedule hashes between
    # rank 0 (the coordinator reference) and everyone else, at the newest
    # step both sides recorded
    diverged: List[int] = []
    ref = per.get(0, {}).get("scheds") or {}
    for r in expected:
        if r == 0:
            continue
        theirs = per[r]["scheds"]
        common = set(ref) & set(theirs)
        if not common:
            continue
        s = max(common)
        if ref[s] != theirs[s]:
            diverged.append(r)
    if len(ops) > 1:
        # ranks parked at the same seq on DIFFERENT collectives: the
        # schedules themselves forked (stronger evidence than the hashes,
        # which lag one step). The reference op must come from a rank AT
        # the frontier — rank 0 preferred, else the lowest arrived rank;
        # anchoring on a rank parked at some OTHER key would misattribute
        # every survivor
        ref_rank = 0 if 0 in arrived else arrived[0]
        ref_op = per[ref_rank]["op"]
        diverged = sorted(set(diverged) | {
            r for r in arrived
            if per[r]["op"] is not None and per[r]["op"] != ref_op
        })
    if diverged:
        out["verdict"] = "schedule_divergence"
        out["hung_ranks"] = sorted(diverged)
        return out
    if missing:
        # named missing even when nobody is (still) parked: survivors may
        # have been released/evicted and progressed past the stuck
        # collective, but a rank whose record stops short of the frontier
        # is exactly what the post-mortem is looking for. The signature
        # reported is the FIRST collective the missing rank never joined
        # (its last begun key + 1 in dispatch order), not the end-of-run
        # frontier — that is the seq the survivors parked on.
        stuck = frontier
        for r in missing:
            lb = per[r]["last_b"]
            later = sorted(k for k in all_begun
                           if lb is None or k > lb)
            if later and later[0] < stuck:
                stuck = later[0]
        out["key"] = [stuck[1], stuck[0], stuck[2]]
        out["op"] = op_at.get(stuck, out["op"])
        out["verdict"] = "rank_missing"
        out["hung_ranks"] = missing
        return out
    if waiting and len(waiting) == len(expected):
        out["verdict"] = "all_parked"
        out["hung_ranks"] = []
        return out
    out["verdict"] = "progressing"
    out["hung_ranks"] = []
    return out


def describe(verdict: dict) -> str:
    """One-line human spelling of an :func:`analyze` verdict (shared by
    the live log line and ``hvd_blackbox``)."""
    v = verdict.get("verdict")
    key = verdict.get("key")
    sig = tuple(key) if key else None
    if v == "rank_missing":
        return (
            f"rank(s) {verdict['hung_ranks']} missing at collective "
            f"(step, gen, seq)={sig} op={verdict.get('op')}; "
            f"rank(s) {verdict.get('waiting')} parked waiting"
        )
    if v == "schedule_divergence":
        return (
            f"schedules diverged at (step, gen, seq)={sig}: rank(s) "
            f"{verdict['hung_ranks']} disagree with rank 0's record"
        )
    if v == "all_parked":
        return (
            f"every rank parked in collective (step, gen, seq)={sig} "
            f"op={verdict.get('op')} — external stall (device/network), "
            f"not a missing rank"
        )
    if v == "progressing":
        return "no hang: the newest collective completed"
    return "no collective events to reason about"


# ----------------------------------------------------------- sidecar loading


def load_sidecar(path: str) -> dict:
    """Parse one sidecar torn-tail tolerantly: unparseable lines (the
    SIGKILL-cut tail, or any corruption) are skipped and counted, like the
    rendezvous WAL replay. The LAST header wins (matching the trace
    merge's newest-``clock_sync`` rule). Returns ``{ranks, world,
    offset_s, generation, events, skipped}``."""
    events_out: List[dict] = []
    header: Optional[dict] = None
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(ev, dict):
                skipped += 1
                continue
            if ev.get("kind") == "header":
                header = ev
            else:
                events_out.append(ev)
    header = header or {}
    return {
        "ranks": [int(r) for r in header.get("ranks", [0])],
        "world": int(header.get("world", 1)),
        "offset_s": float(header.get("offset_s", 0.0)),
        "generation": int(header.get("generation", 0)),
        "events": events_out,
        "skipped": skipped,
    }


def load_dir(path_or_paths) -> Tuple[Dict[int, List[dict]], dict]:
    """Load sidecar files (a directory is globbed for
    ``flight-rank*.jsonl``) into skew-corrected per-rank event streams:
    each file's events are shifted by its header's clock offset, assigned
    to every rank in its LAST header's ``ranks`` list, and sorted by
    corrected time. Returns ``(rank_events, meta)`` where meta carries the
    max ``world`` seen (so a rank with NO file at all can still be named
    missing) and per-file load notes."""
    if isinstance(path_or_paths, str):
        if os.path.isdir(path_or_paths):
            paths = sorted(
                os.path.join(path_or_paths, fn)
                for fn in os.listdir(path_or_paths)
                if fn.startswith("flight-rank") and fn.endswith(".jsonl")
            )
        else:
            paths = [path_or_paths]
    else:
        paths = list(path_or_paths)
    rank_events: Dict[int, List[dict]] = {}
    meta: dict = {"files": [], "world": 0}
    for p in paths:
        try:
            side = load_sidecar(p)
        except OSError as e:
            meta["files"].append({"path": p, "error": str(e)})
            continue
        meta["files"].append({
            "path": p, "ranks": side["ranks"], "events": len(side["events"]),
            "skipped": side["skipped"],
        })
        meta["world"] = max(meta["world"], side["world"])
        off = side["offset_s"]
        for ev in side["events"]:
            try:
                shifted = dict(ev, t=float(ev.get("t", 0.0)) + off)
            except (TypeError, ValueError):
                shifted = dict(ev)
            for r in side["ranks"]:
                rank_events.setdefault(r, []).append(shifted)
    for r in rank_events:
        rank_events[r].sort(key=lambda e: e.get("t") or 0.0)
    return rank_events, meta


def analyze_loaded(rank_events: Dict[int, List[dict]], meta: dict) -> dict:
    """:func:`analyze` over :func:`load_dir` output, with the expected
    rank set widened to the headers' world — a rank that left NO record
    is still named missing. The one offline entry point both
    :func:`analyze_dir` and ``hvd_blackbox`` go through, so the widening
    rule cannot drift between them."""
    expected = set(rank_events)
    if meta.get("world"):
        expected |= set(range(meta["world"]))
    verdict = analyze(rank_events, expected=sorted(expected))
    verdict["meta"] = meta
    return verdict


def analyze_dir(path_or_paths) -> dict:
    """Offline diagnosis from sidecar files alone (what ``hvd_blackbox``
    runs): load, skew-correct, :func:`analyze_loaded`."""
    rank_events, meta = load_dir(path_or_paths)
    return analyze_loaded(rank_events, meta)


# ------------------------------------------------------------------ watchdog


def maybe_arm_watchdog(kv=None, world: Optional[int] = None):
    """Arm the hang watchdog iff ``HOROVOD_HANG_TIMEOUT`` > 0 (what
    ``horovod_tpu.init`` calls); returns the thread or None."""
    t = hang_timeout()
    if t <= 0 or not enabled():
        return None
    return arm_watchdog(timeout=t, kv=kv, world=world)


def arm_watchdog(*, timeout: float, kv=None, world: Optional[int] = None):
    """Start the watchdog thread: when no collective-end/step progress
    lands for `timeout` seconds (measured from arming or the last progress
    event, and only once any collective/step activity has been seen), it
    pushes ring tails to the KV and — on rank 0 — diagnoses and feeds
    :func:`horovod_tpu.resilience.health.record_hang`. One firing per
    stall episode; progress re-arms it."""
    global _watchdog, _armed_at, _fired_at
    disarm_watchdog()
    if kv is not None or world is not None:
        configure(kv=kv, world=world)
    _armed_at = time.monotonic()
    _fired_at = None
    stop = threading.Event()
    th = threading.Thread(
        target=_watch, args=(float(timeout), stop),
        name="hvd-hang-watchdog", daemon=True,
    )
    _watchdog = (th, stop)
    th.start()
    return th


def disarm_watchdog() -> None:
    global _watchdog
    entry = _watchdog
    if entry is None:
        return
    th, stop = entry
    stop.set()
    _release.set()
    th.join(timeout=5)
    # a thread that outlived the join (blocked in a slow firing) still
    # holds its own (now set) stop event: it exits its loop — and skips
    # publishing a stale verdict — as soon as it unblocks
    _watchdog = None


def _watch(timeout: float, stop: threading.Event) -> None:
    global _fired_at
    poll = max(0.02, min(timeout / 4.0, 1.0))
    while not stop.wait(poll):
        progress = _last_progress
        if _fired_at is not None:
            # one firing per stall episode: re-arm only once progress
            # resumed after the firing
            if progress is not None and progress > _fired_at:
                with _lock:
                    _fired_at = None
            continue
        if progress is None:
            continue  # no collective/step activity yet: nothing to hang
        # measured from arming OR the last progress, whichever is newer:
        # a re-arm after an elastic resize must not fire instantly off
        # the stale pre-resize progress stamp
        base = progress if _armed_at is None else max(progress, _armed_at)
        if time.monotonic() - base >= timeout:
            try:
                _fire(timeout, stop)
            except Exception:
                logger.warning(
                    "hang watchdog firing failed", exc_info=True)
                with _lock:
                    _fired_at = time.monotonic()


def _fire(timeout: float, stop: Optional[threading.Event] = None) -> None:
    """The watchdog tripped: persist + push this process's evidence, and
    (rank 0) run the cross-rank diagnosis. `stop` is the owning thread's
    disarm event: a firing that outlives its watchdog (disarm during the
    peer-tail wait) aborts instead of publishing a stale verdict into a
    newer generation."""
    global _fired_at, _last_hang
    with _lock:
        _fired_at = time.monotonic()
    record("hang", ph="fired", timeout=timeout)
    if _metrics.enabled():
        _metrics.counter(
            "hang_watchdog_fired",
            help="hang-watchdog firings (no collective/step progress for "
                 "HOROVOD_HANG_TIMEOUT)",
        ).inc()
    flush()
    # tails must outlive the whole diagnosis window: rank 0 waits up to
    # one timeout for peers, and every poll re-reads — a lease shorter
    # than that would expire the surviving peers' evidence mid-wait
    ttl = max(120.0, 4.0 * timeout)
    try:
        push_tails(ttl=ttl)
    except Exception as e:
        logger.warning("flight tail push failed: %s", e)
    world, prank, psize = _identity()
    if prank != 0:
        return  # the coordinator owns the verdict
    participants = list(range(max(1, psize if psize > 1 else world)))
    # peers' watchdogs fire on their own clocks: give their pushes one
    # timeout's grace before diagnosing with what there is (an absent tail
    # is itself evidence — the prime suspect pushes nothing)
    deadline = time.monotonic() + max(0.2, timeout)
    tails = {}
    while True:
        tails = read_tails(participants)
        if len(tails) >= len(participants) or time.monotonic() >= deadline:
            break
        if stop is not None and stop.wait(max(0.02, timeout / 10.0)):
            return  # disarmed mid-wait: no stale verdict
        elif stop is None:
            time.sleep(max(0.02, timeout / 10.0))
    if stop is not None and stop.is_set():
        return  # disarmed: the new generation owns diagnosis now
    verdict = analyze(
        {r: t.get("events", []) for r, t in tails.items()},
        expected=participants,
    )
    # live-only sharpening: the sanitizer may have already named a
    # divergence at the stuck step's boundary — trust it over "missing"
    if verdict.get("verdict") in ("rank_missing", "all_parked"):
        try:
            from horovod_tpu.analysis import sanitizer as _sanitizer

            d = _sanitizer.last_divergence()
            if d and verdict.get("key") and \
                    int(d["step"]) >= int(verdict["key"][0]) - 1:
                verdict = dict(
                    verdict, verdict="schedule_divergence",
                    hung_ranks=[int(d["rank"])], sanitizer=d,
                )
        except Exception as e:
            logger.debug("sanitizer cross-check failed: %s", e)
    record(
        "hang", ph="diagnosed", verdict=verdict.get("verdict"),
        key=verdict.get("key"), op=verdict.get("op"),
        hung_ranks=verdict.get("hung_ranks"),
    )
    flush()
    if _metrics.enabled():
        _metrics.counter(
            "hang_diagnosed",
            help="hang-watchdog diagnoses, by verdict",
            verdict=str(verdict.get("verdict")),
        ).inc()
    logger.error("hang diagnosis: %s", describe(verdict))
    if verdict.get("verdict") in (
        "rank_missing", "schedule_divergence", "all_parked",
    ):
        from horovod_tpu.resilience import health

        hung = verdict.get("hung_ranks") or []
        health.record_hang(
            hung[0] if hung else None,
            verdict.get("key"),
            kind=verdict.get("verdict", "rank_missing"),
        )
        if hung and evict_enabled():
            with _lock:
                for r in hung:
                    if r != 0 and r not in _hung_ranks:
                        _hung_ranks.append(int(r))
    with _lock:
        # published LAST: a poller seeing last_hang() non-None may rely
        # on the health strike and eviction queue already being in place
        _last_hang = verdict
    _release.set()  # free a chaos hold parked on the diagnosis


def last_hang() -> Optional[dict]:
    """The most recent live diagnosis this process produced, or None."""
    return _last_hang


def take_hung_ranks() -> List[int]:
    """Drain the ranks a diagnosis queued for elastic eviction (populated
    only under ``HOROVOD_HANG_EVICT=1``; the elastic membership sweep
    consumes this exactly like the numerics quarantine set)."""
    global _hung_ranks
    with _lock:
        out, _hung_ranks = _hung_ranks, []
    return out


def requeue_hung_ranks(ranks: Iterable[int]) -> None:
    """Put verdicts back after a failed eviction attempt (a transient KV
    error at ``mark_dead`` must not lose the verdict — the watchdog fires
    once per stall episode and a hung mesh makes no progress to re-arm
    it, so a dropped verdict would never be re-derived). Mirrors
    ``numerics.requeue_corrupt_ranks``."""
    with _lock:
        for r in ranks:
            if int(r) not in _hung_ranks:
                _hung_ranks.append(int(r))
