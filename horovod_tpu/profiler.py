"""Tracing/profiling surface — the TPU-native Timeline (SURVEY.md §5.1).

The reference writes a Chrome-tracing JSON from the C++ core's negotiation
and op phases (``common/timeline.{h,cc}``, enabled by ``HOROVOD_TIMELINE``,
coordinator-only). The rebuild has two complementary layers:

- **Negotiation timeline** — the native core (``csrc/``) writes the same
  chrome://tracing JSON for enqueue/negotiate/execute phases when
  ``HOROVOD_TIMELINE`` is set (see ``horovod_tpu/core.py``).
- **Device timeline** (this module) — on TPU the op execution itself lives
  inside XLA, invisible to a host-side tracer; the idiomatic tool is the XLA
  profiler. ``start_timeline``/``stop_timeline`` wrap ``jax.profiler`` so one
  call captures device traces (HLO steps, collective time on ICI, HBM
  transfers) viewable in TensorBoard/Perfetto — the role chrome://tracing
  plays for the reference.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax

_active_dir: Optional[str] = None


def start_timeline(log_dir: str) -> None:
    """Begin capturing a device trace into ``log_dir`` (analog of setting
    ``HOROVOD_TIMELINE``; reference ``operations.cc:404-411`` inits the
    Timeline on the coordinator only — call this on rank/process 0)."""
    global _active_dir
    if _active_dir is not None:
        raise RuntimeError(f"timeline already active in {_active_dir}")
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _active_dir = log_dir


def stop_timeline() -> str:
    """Stop the capture; returns the trace directory."""
    global _active_dir
    if _active_dir is None:
        raise RuntimeError("no active timeline; call start_timeline first")
    jax.profiler.stop_trace()
    out, _active_dir = _active_dir, None
    return out


@contextlib.contextmanager
def timeline(log_dir: str):
    """Context-manager spelling::

        with hvd.profiler.timeline("/tmp/trace"):
            train_steps()
    """
    start_timeline(log_dir)
    try:
        yield log_dir
    finally:
        stop_timeline()


def annotate(name: str):
    """Named host-span annotation that shows up in the device trace
    (analog of the reference's per-tensor ACTIVITY spans,
    ``common/common.h:31-59``)."""
    return jax.profiler.TraceAnnotation(name)


# Peak bf16 matmul throughput per chip, FLOP/s, keyed by substrings of
# ``jax.Device.device_kind`` — the denominator for MFU reporting (used by
# ``bench.py`` and the benchmark examples). Sources: published TPU specs.
_PEAK_BF16_FLOPS = (
    ("v6", 918e12),
    ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# Published per-chip HBM bandwidth (bytes/s) — denominator for the MFU
# probe's bandwidth-utilization figure.
_PEAK_HBM_BYTES = (
    ("v6", 1640e9),
    ("trillium", 1640e9),
    ("v5p", 2765e9),
    ("v5e", 819e9),
    ("v5 lite", 819e9),
    ("v5litepod", 819e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def timed_steps(run_one, n_steps: int, *, lag: int = 2):
    """Time ``n_steps`` calls of ``run_one()`` with a lagged device→host
    fence; returns ``(fenced_values, dt_seconds)``.

    ``run_one`` executes one step (keeping its state in a closure) and
    returns a device scalar (typically the loss). ``block_until_ready``
    alone does NOT reliably fence the dispatch chain on all runtimes — an
    async loop once "measured" ~80x real throughput on the tunnel TPU — so
    each returned scalar is fetched to the host. Each scalar transitively
    depends on the previous step's state, so fetching it forces every step
    up to that point; reading with a ``lag``-step delay keeps the device
    pipeline full (steps overlap the host sync) while the final drain
    forces the complete chain before the clock stops.
    """
    import collections
    import time

    fenced = []
    in_flight = collections.deque()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        in_flight.append(run_one())
        if len(in_flight) > lag:
            fenced.append(float(in_flight.popleft()))
    while in_flight:
        fenced.append(float(in_flight.popleft()))
    return fenced, time.perf_counter() - t0


def _lookup_peak(table, device_kind: Optional[str]) -> Optional[float]:
    if device_kind is None:
        device_kind = jax.devices()[0].device_kind
    kind = device_kind.lower()
    for key, peak in table:
        if key in kind:
            return peak
    return None


def device_peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak bf16 FLOP/s for a device kind (default: first local device).
    Returns None for kinds with no table entry (e.g. ``cpu``) — callers
    should skip MFU reporting rather than divide by a guess."""
    return _lookup_peak(_PEAK_BF16_FLOPS, device_kind)


def device_peak_hbm_bytes(device_kind: Optional[str] = None) -> Optional[float]:
    """Published per-chip HBM bandwidth in bytes/s (None when untabled),
    same lookup convention as :func:`device_peak_flops`."""
    return _lookup_peak(_PEAK_HBM_BYTES, device_kind)
