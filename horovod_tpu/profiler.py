"""Tracing/profiling surface — the TPU-native Timeline (SURVEY.md §5.1).

The reference writes a Chrome-tracing JSON from the C++ core's negotiation
and op phases (``common/timeline.{h,cc}``, enabled by ``HOROVOD_TIMELINE``,
coordinator-only). The rebuild has two complementary layers:

- **Negotiation timeline** — the native core (``csrc/``) writes the same
  chrome://tracing JSON for enqueue/negotiate/execute phases when
  ``HOROVOD_TIMELINE`` is set (see ``horovod_tpu/core.py``).
- **Device timeline** (this module) — on TPU the op execution itself lives
  inside XLA, invisible to a host-side tracer; the idiomatic tool is the XLA
  profiler. ``start_timeline``/``stop_timeline`` wrap ``jax.profiler`` so one
  call captures device traces (HLO steps, collective time on ICI, HBM
  transfers) viewable in TensorBoard/Perfetto — the role chrome://tracing
  plays for the reference.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax

_active_dir: Optional[str] = None


def start_timeline(log_dir: str) -> None:
    """Begin capturing a device trace into ``log_dir`` (analog of setting
    ``HOROVOD_TIMELINE``; reference ``operations.cc:404-411`` inits the
    Timeline on the coordinator only — call this on rank/process 0)."""
    global _active_dir
    if _active_dir is not None:
        raise RuntimeError(f"timeline already active in {_active_dir}")
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _active_dir = log_dir


def stop_timeline() -> str:
    """Stop the capture; returns the trace directory."""
    global _active_dir
    if _active_dir is None:
        raise RuntimeError("no active timeline; call start_timeline first")
    jax.profiler.stop_trace()
    out, _active_dir = _active_dir, None
    return out


@contextlib.contextmanager
def timeline(log_dir: str):
    """Context-manager spelling::

        with hvd.profiler.timeline("/tmp/trace"):
            train_steps()
    """
    start_timeline(log_dir)
    try:
        yield log_dir
    finally:
        stop_timeline()


def annotate(name: str):
    """Named host-span annotation that shows up in the device trace
    (analog of the reference's per-tensor ACTIVITY spans,
    ``common/common.h:31-59``)."""
    return jax.profiler.TraceAnnotation(name)
