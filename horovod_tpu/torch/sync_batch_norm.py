"""Cross-rank synchronized BatchNorm for the torch frontend (reference
``horovod/torch/sync_batch_norm.py``): batch statistics are computed over the
GLOBAL batch — local sums and counts are allreduced/allgathered — so small
per-rank batches still normalize correctly. Forward and backward each perform
one fused allreduce; the backward recurrence follows the standard batch-norm
gradient with global reductions (reference ``sync_batch_norm.py:130-194``)."""

from __future__ import annotations

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from horovod_tpu import basics
from horovod_tpu.torch import mpi_ops


class SyncBatchNorm(_BatchNorm):
    """Drop-in for ``torch.nn.BatchNorm*d`` that synchronizes statistics
    across ranks during training (reference ``torch/sync_batch_norm.py:30-86``).
    Evaluation mode uses running statistics, exactly like plain BatchNorm."""

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D input)"
            )

    def forward(self, input):
        if not (self.training and basics.size() > 1):
            return super().forward(input)
        self._check_input_dim(input)
        if self.momentum is None:  # cumulative moving average
            exponential_average_factor = 0.0
        else:
            exponential_average_factor = self.momentum
        if self.training and self.track_running_stats:
            self.num_batches_tracked += 1
            if self.momentum is None:
                exponential_average_factor = 1.0 / float(
                    self.num_batches_tracked
                )
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean, self.running_var,
            self.eps, exponential_average_factor,
        )


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum):
        c = input.shape[1]
        x = input.transpose(0, 1).reshape(c, -1)  # [C, N*spatial]
        local_count = x.shape[1]

        # Two-pass statistics: allreduce [sum, count] -> global mean, then
        # allreduce the CENTERED sum of squares. Centering first keeps fp32
        # safe (no E[x^2]-mean^2 cancellation) — the collective wire is fp32
        # (jax x64 is off by default), so sums of squares of raw values
        # would silently lose the float64 staged here otherwise.
        stats = torch.empty(c + 1, dtype=torch.float32, device=input.device)
        stats[:c] = x.sum(dim=1).float()
        stats[c] = float(local_count)
        stats = mpi_ops.allreduce(stats, op=mpi_ops.Sum)
        global_count = stats[c].item()
        mean = (stats[:c] / global_count).to(input.dtype)
        ssd = ((x - mean.unsqueeze(1).to(x.dtype)) ** 2).sum(dim=1).float()
        ssd = mpi_ops.allreduce(ssd, op=mpi_ops.Sum)
        var = (ssd / global_count).to(input.dtype)

        if running_mean is not None:
            with torch.no_grad():
                # unbiased var for running stats, as torch BatchNorm does
                unbiased = var * (global_count / max(global_count - 1, 1))
                running_mean.mul_(1 - momentum).add_(momentum * mean)
                running_var.mul_(1 - momentum).add_(momentum * unbiased)

        invstd = torch.rsqrt(var + eps)
        shape = [1, c] + [1] * (input.dim() - 2)
        xhat = (input - mean.view(shape)) * invstd.view(shape)
        out = xhat
        if weight is not None:
            out = out * weight.view(shape)
        if bias is not None:
            out = out + bias.view(shape)
        ctx.save_for_backward(xhat, weight, invstd)
        ctx.global_count = global_count
        return out

    @staticmethod
    def backward(ctx, grad_output):
        xhat, weight, invstd = ctx.saved_tensors
        c = grad_output.shape[1]
        shape = [1, c] + [1] * (grad_output.dim() - 2)
        reduce_dims = [d for d in range(grad_output.dim()) if d != 1]

        # local per-channel reductions, then one fused cross-rank allreduce
        local = torch.empty(c, 2, dtype=torch.float32,
                            device=grad_output.device)
        local[:, 0] = grad_output.sum(dim=reduce_dims).float()
        local[:, 1] = (grad_output * xhat).sum(dim=reduce_dims).float()
        tot = mpi_ops.allreduce(local, op=mpi_ops.Sum)
        sum_dy = tot[:, 0].to(grad_output.dtype)
        sum_dy_xhat = tot[:, 1].to(grad_output.dtype)
        # weight/bias grads stay LOCAL sums — DistributedOptimizer averages
        # them with every other parameter gradient afterwards (reference
        # torch/sync_batch_norm.py backward returns the local reduce)
        local_sum_dy = local[:, 0].to(grad_output.dtype)
        local_sum_dy_xhat = local[:, 1].to(grad_output.dtype)
        n = ctx.global_count

        gamma = (
            weight if weight is not None else torch.ones_like(sum_dy)
        )
        grad_input = (
            gamma.view(shape) * invstd.view(shape) * (
                grad_output
                - (sum_dy / n).view(shape)
                - xhat * (sum_dy_xhat / n).view(shape)
            )
        )
        grad_weight = local_sum_dy_xhat if weight is not None else None
        grad_bias = local_sum_dy if weight is not None else None
        return grad_input, grad_weight, grad_bias, None, None, None, None
