"""PyTorch frontend: ``import horovod_tpu.torch as hvd``.

Reference parity with ``horovod/torch/__init__.py`` (0.19.2): a
``DistributedOptimizer`` that allreduces gradients as they are accumulated
(per-parameter hooks + ``backward_passes_per_step`` delay counters,
reference ``torch/__init__.py:67-222``), ``broadcast_parameters`` /
``broadcast_optimizer_state`` / ``broadcast_object``
(``torch/__init__.py:451-648``), functional sync/async/in-place collectives
(``torch/mpi_ops.py``), fp16 compression (``torch/compression.py``), and
``SyncBatchNorm`` (``torch/sync_batch_norm.py``).

The compute fabric underneath is the TPU-native engine: collectives lower
to XLA over the device mesh in-process, or ride the cross-process host
path when launched with ``hvdrun`` — torch never talks to NCCL/MPI here.
"""

from __future__ import annotations

import collections
import contextlib

import torch

from horovod_tpu.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, process_rank, process_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, gloo_enabled,
    num_rank_is_power_2, gpu_available,
    nccl_built, mpi_built, gloo_built, ccl_built,
    ddl_built, xla_built,
)
from horovod_tpu.torch.compression import Compression  # noqa: F401
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    Adasum, Average, ReduceOp, Sum,
    allreduce, allreduce_, allreduce_async, allreduce_async_,
    grouped_allreduce, grouped_allreduce_,
    allgather, allgather_async,
    broadcast, broadcast_, broadcast_async, broadcast_async_,
    alltoall, alltoall_async,
    reducescatter, reducescatter_async,
    synchronize, poll, join,
)
from horovod_tpu.torch.sync_batch_norm import SyncBatchNorm  # noqa: F401
from horovod_tpu.ops.collective import (
    allgather_object,  # noqa: F401
    broadcast_object as _broadcast_object_impl,
)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Gradient-allreducing optimizer wrapper (reference
    ``torch/__init__.py:67-222``): a hook on every parameter fires when its
    gradient is fully accumulated, launches an async allreduce, and
    ``step()`` synchronizes all handles before applying the update."""

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1, op=Average,
                 error_feedback=False):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self.op = op
        self.backward_passes_per_step = backward_passes_per_step
        if error_feedback and compression is Compression.none:
            raise ValueError(
                "error_feedback=True needs a lossy compression "
                "(e.g. Compression.fp16)"
            )
        self._error_feedback = error_feedback
        self._ef_residual = {}  # param -> rounding error kept back (EF-SGD)

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [
                (f"allreduce.noname.{i}", v)
                for i, pg in enumerate(self.param_groups)
                for v in pg["params"]
            ]
        # names must be unique and cover all parameters
        # (reference torch/__init__.py:82-110)
        all_names = [name for name, _ in named_parameters]
        if len(set(all_names)) < len(all_names):
            raise ValueError(
                "named_parameters should map parameter names to unique names"
            )
        named_set = {p for _, p in named_parameters}
        unnamed = [
            p for pg in self.param_groups for p in pg["params"]
            if p not in named_set
        ]
        if unnamed:
            raise ValueError(
                "named_parameters was specified, but one or more model "
                "parameters were not named"
            )
        self._parameter_names = {v: k for k, v in named_parameters}
        self._handles = {}
        self._grad_accs = []
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        self._allreduce_delay = {}
        if size() > 1:
            self._register_hooks()

    def _register_hooks(self):
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    if hasattr(p, "register_post_accumulate_grad_hook"):
                        p.register_post_accumulate_grad_hook(
                            self._make_post_hook(p)
                        )
                    else:  # pragma: no cover - older torch
                        p.grad = p.data.new(p.size()).zero_()
                        p_tmp = p.expand_as(p)
                        grad_acc = p_tmp.grad_fn.next_functions[0][0]
                        grad_acc.register_hook(self._make_hook(p))
                        self._grad_accs.append(grad_acc)

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(p)
        tensor = p.grad
        if self._error_feedback:
            # fold back what compression rounded away last step; keep this
            # step's rounding error for the next (mirrors the optax
            # error_feedback path, horovod_tpu/optim.py). Residuals live in
            # their own dict — NOT self.state[p], which must stay empty
            # until the inner optimizer's lazy init (Adam-family checks
            # `len(state) == 0`) — and ride state_dict() via the explicit
            # hooks below.
            with torch.no_grad():
                if p not in self._ef_residual:
                    self._ef_residual[p] = torch.zeros_like(tensor)
                tensor = tensor + self._ef_residual[p]
                tensor_compressed, ctx = self._compression.compress(tensor)
                sent = self._compression.decompress(tensor_compressed, ctx)
                self._ef_residual[p] = tensor - sent
        else:
            tensor_compressed, ctx = self._compression.compress(tensor)
        handle = allreduce_async_(
            tensor_compressed, name=f"allreduce.{name}", op=self.op
        )
        return handle, (tensor_compressed, ctx)

    def _make_post_hook(self, p):
        def hook(param):
            self._do_hook(p)

        return hook

    def _make_hook(self, p):  # pragma: no cover - older torch
        def hook(*ignore):
            self._do_hook(p)

        return hook

    def _do_hook(self, p):
        if p in self._handles and self._handles[p][0] is not None:
            if self._allreduce_delay[p] <= 0:
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally."
                )
        if p.grad is not None and p.grad.requires_grad:
            raise AssertionError(
                "attempting to allreduce a gradient that requires grad"
            )
        handle, ctx = None, None
        self._allreduce_delay[p] -= 1
        if self._allreduce_delay[p] == 0:
            handle, ctx = self._allreduce_grad_async(p)
        self._handles[p] = (handle, ctx)

    def synchronize(self):
        """Wait for all outstanding gradient allreduces and write the reduced
        gradients back (reference ``torch/__init__.py:165-215``)."""
        missing_p = self._requires_update - set(self._handles.keys())
        for p in missing_p:
            if p.grad is None:
                p.grad = p.data.new(p.size()).zero_()
            self._handles[p] = self._allreduce_grad_async(p)

        for p, (handle, ctx) in self._handles.items():
            if handle is None:
                handle, ctx = self._allreduce_grad_async(p)
                self._handles[p] = (handle, ctx)
        for p, (handle, ctx) in list(self._handles.items()):
            output = synchronize(handle)
            self._allreduce_delay[p] = self.backward_passes_per_step
            _, comp_ctx = ctx
            with torch.no_grad():
                p.grad.copy_(
                    self._compression.decompress(output, comp_ctx).to(
                        p.grad.dtype
                    )
                )
        self._handles.clear()
        self._synchronized = True

    def state_dict(self, *args, **kwargs):
        """Inner optimizer state plus the error-feedback residuals (stored
        under their own key, indexed like torch's param ordering, so
        checkpoint/resume preserves not-yet-transmitted gradient mass)."""
        d = super(self.__class__, self).state_dict(*args, **kwargs)
        if self._error_feedback and self._ef_residual:
            index = {
                p: i
                for i, p in enumerate(
                    p for pg in self.param_groups for p in pg["params"]
                )
            }
            d["ef_residual"] = {
                index[p]: t.clone() for p, t in self._ef_residual.items()
            }
        return d

    def load_state_dict(self, state_dict, *args, **kwargs):
        state_dict = dict(state_dict)
        resid = state_dict.pop("ef_residual", None)
        super(self.__class__, self).load_state_dict(
            state_dict, *args, **kwargs
        )
        if resid is not None:
            params = [p for pg in self.param_groups for p in pg["params"]]
            # cast like torch does for per-param state: a CPU-loaded
            # checkpoint must land on each param's device/dtype
            # .clone(): Tensor.to returns self when device/dtype already
            # match, which would alias the caller's state_dict tensors
            self._ef_residual = {
                params[i]: t.to(params[i].device, params[i].dtype).clone()
                for i, t in resid.items()
            }

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Inside this context ``step()`` will not synchronize — for use
        after an explicit ``synchronize()`` call (reference
        ``torch/__init__.py:189-203``)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings

                warnings.warn(
                    "optimizer.step() called without "
                    "optimizer.skip_synchronize() context after "
                    "optimizer.synchronize(). This can cause training "
                    "slowdown. You may want to consider using "
                    "optimizer.skip_synchronize() context if you use "
                    "optimizer.synchronize() in your code."
                )
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize(). This is "
                "prohibited as it can cause a race condition."
            )
        return super(self.__class__, self).zero_grad(*args, **kwargs)


class _DistributedAdasumOptimizer(torch.optim.Optimizer):
    """Delta-style Adasum optimizer (reference ``torch/__init__.py:225-394``).

    Instead of allreducing *gradients*, each rank applies its local optimizer
    update to produce a parameter *delta* and the deltas are combined with
    the Adasum VHDD reduction, which preserves update magnitude regardless of
    worker count:

        start  = p                       (stashed per parameter)
        step() -> p = start - lr * f(g)  (local optimizer logic)
        delta  = p - start
        delta  = adasum_allreduce(delta)
        p      = start + delta
    """

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [
                (f"allreduce.noname.{i}", v)
                for i, pg in enumerate(self.param_groups)
                for v in pg["params"]
            ]
        all_names = [name for name, _ in named_parameters]
        if len(set(all_names)) < len(all_names):
            raise ValueError(
                "named_parameters should map parameter names to unique names"
            )
        named_set = {p for _, p in named_parameters}
        unnamed = [
            p for pg in self.param_groups for p in pg["params"]
            if p not in named_set
        ]
        if unnamed:
            raise ValueError(
                "named_parameters was specified, but one or more model "
                "parameters were not named"
            )
        self._parameter_names = {v: k for k, v in named_parameters}
        self._handles = {}
        self._requires_update = set()
        self._allreduce_delay = {}
        # per-parameter stash of the pre-step value; the reduced delta is
        # applied on top of it in step()
        self._starting_models = {
            p: torch.zeros_like(p, requires_grad=False)
            for _, p in named_parameters
        }
        self._register_hooks()

    def set_backward_passes_per_step(self, passes):
        self.backward_passes_per_step = passes
        for p in self._allreduce_delay:
            self._allreduce_delay[p] = passes

    def _register_hooks(self):
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    if hasattr(p, "register_post_accumulate_grad_hook"):
                        p.register_post_accumulate_grad_hook(
                            self._make_post_hook(p)
                        )
                    else:  # pragma: no cover - older torch
                        p.grad = p.data.new(p.size()).zero_()
                        p_tmp = p.expand_as(p)
                        grad_acc = p_tmp.grad_fn.next_functions[0][0]
                        grad_acc.register_hook(self._make_post_hook(p))

    def _allreduce_delta_async(self, p):
        """Run the wrapped optimizer on `p` alone, turn the result into a
        delta, and launch its Adasum allreduce."""
        name = self._parameter_names.get(p)
        start = self._starting_models[p]

        # restrict the underlying step() to just this parameter
        stashed = []
        for group in self.param_groups:
            stashed.append(group["params"])
            group["params"] = [p] if any(p is v for v in group["params"]) else []
        start.data.copy_(p.data)
        super(self.__class__, self).step()
        for prev, group in zip(stashed, self.param_groups):
            group["params"] = prev

        with torch.no_grad():
            p.data.sub_(start)  # p now holds the local delta
        tensor_compressed, ctx = self._compression.compress(p.data)
        handle = allreduce_async_(
            tensor_compressed, name=f"adasum.{name}", op=Adasum
        )
        return handle, ctx

    def _make_post_hook(self, p):
        def hook(*ignore):
            if p in self._handles and self._handles[p][0] is not None:
                if self._allreduce_delay[p] <= 0:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step to "
                        "accumulate gradients locally."
                    )
            handle, ctx = None, None
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                handle, ctx = self._allreduce_delta_async(p)
            self._handles[p] = (handle, ctx)

        return hook

    def synchronize(self):
        """No-op: Adasum synchronization happens inside step() (reference
        ``torch/__init__.py:357-359``)."""

    @contextlib.contextmanager
    def skip_synchronize(self):
        raise AssertionError(
            "Skipping synchronization is not supported when using Adasum "
            "optimizer."
        )

    def step(self, closure=None):
        loss = closure() if closure is not None else None
        missing_p = self._requires_update - set(self._handles.keys())
        for p in missing_p:
            self._handles[p] = self._allreduce_delta_async(p)
        for p, (handle, ctx) in list(self._handles.items()):
            if handle is None:  # step() before backward_passes_per_step done
                handle, ctx = self._allreduce_delta_async(p)
            delta = synchronize(handle)
            delta = self._compression.decompress(delta, ctx)
            start = self._starting_models[p]
            with torch.no_grad():
                start.data.add_(delta)
                p.data.copy_(start)
            self._allreduce_delay[p] = self.backward_passes_per_step
        self._handles.clear()
        return loss

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step(). This is prohibited as it can cause "
                "a race condition."
            )
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1, op=Average,
                         error_feedback=False):
    """Wrap a ``torch.optim.Optimizer`` so gradients are allreduced across
    ranks during ``backward()`` (reference ``torch/__init__.py:397-448``).
    With ``op=Adasum`` the wrapper switches to the delta-style
    :class:`_DistributedAdasumOptimizer`. ``error_feedback=True`` (beyond
    the reference) keeps each rank's compression rounding error and folds it
    into the next step's gradient — see ``docs/performance.md``."""
    impl = _DistributedAdasumOptimizer if op == Adasum else _DistributedOptimizer
    cls = type(
        optimizer.__class__.__name__,
        (optimizer.__class__,),
        dict(impl.__dict__),
    )
    if op == Adasum:
        if error_feedback:
            raise ValueError("error_feedback is not supported with op=Adasum")
        return cls(
            optimizer.param_groups, named_parameters, compression,
            backward_passes_per_step,
        )
    return cls(
        optimizer.param_groups, named_parameters, compression,
        backward_passes_per_step, op, error_feedback,
    )


def broadcast_parameters(params, root_rank=0):
    """Broadcast parameters from `root_rank` to all ranks — the
    start-of-training sync (reference ``torch/__init__.py:451-478``). Accepts
    a ``state_dict()`` or an iterable of ``(name, tensor)``."""
    if isinstance(params, dict):
        params = sorted(params.items())
    elif isinstance(params, collections.abc.Iterable):
        params = list(params)
    else:
        raise ValueError("invalid params of type: %s" % type(params))

    handles = []
    for name, p in params:
        if p is None:
            continue
        handles.append(broadcast_async_(p, root_rank, name=f"bcastparam.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcast an optimizer's state (momenta, step counters, param-group
    hyperparameters) from `root_rank` (reference
    ``torch/__init__.py:481-607``): tensor state is broadcast tensor-wise,
    scalar state is wrapped into tensors, non-numeric options ride
    ``broadcast_object``."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()

    if not state_dict["state"]:
        # Newly constructed optimizers have no state: run a dummy
        # zero-gradient step to materialize it so all ranks agree on the
        # schema (reference torch/__init__.py:497-508). This must run on
        # EVERY rank — with a DistributedOptimizer the step allreduces, and
        # a root-only step would deadlock the other ranks.
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = p.data.new(p.size()).zero_()
        optimizer.step()
        state_dict = optimizer.state_dict()

    # scalars (lr, momentum, step counts, ...) and structure go by object
    # broadcast; tensor state goes tensor-wise so large momenta do not get
    # pickled.
    tensors = {}
    meta = {"param_groups": [], "state": {}}
    for i, group in enumerate(state_dict["param_groups"]):
        meta["param_groups"].append(
            {k: v for k, v in group.items() if k != "params"}
        )
    for pid, pstate in state_dict["state"].items():
        meta_p = {}
        for k, v in pstate.items():
            if torch.is_tensor(v):
                tensors[f"{pid}/{k}"] = v
                meta_p[k] = "__tensor__"
            else:
                meta_p[k] = v
        meta["state"][pid] = meta_p
    meta = broadcast_object(meta, root_rank, name="opt_state_meta")

    for i, g_meta in enumerate(meta["param_groups"]):
        state_dict["param_groups"][i].update(g_meta)
    for pid, meta_p in meta["state"].items():
        pstate = state_dict["state"].setdefault(pid, {})
        for k, v in meta_p.items():
            if v == "__tensor__":
                t = tensors.get(f"{pid}/{k}")
                if t is None:
                    raise ValueError(
                        f"rank {rank()} missing optimizer state tensor "
                        f"{pid}/{k} present on root {root_rank}"
                    )
                broadcast_(t, root_rank, name=f"optstate.{pid}.{k}")
                pstate[k] = t
            else:
                pstate[k] = v
    optimizer.load_state_dict(state_dict)


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcast an arbitrary picklable object (reference
    ``torch/__init__.py:609-648``)."""
    return _broadcast_object_impl(obj, root_rank, name=name)
