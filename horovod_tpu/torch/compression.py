"""Gradient compression for the torch frontend (reference
``horovod/torch/compression.py:20-73``): compress before the collective,
decompress after. fp16 halves bytes over ICI/DCN exactly as it halved bytes
over NCCL rings in the reference."""

import torch


class Compressor:
    """Interface (reference ``torch/compression.py:20-30``)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference ``torch/compression.py:33-43``)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 for the wire, back to the original dtype
    after (reference ``torch/compression.py:46-63``)."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if tensor.dtype.is_floating_point:
            tensor = tensor.to(torch.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and ctx.is_floating_point and tensor.dtype != ctx:
            tensor = tensor.to(ctx)
        return tensor


class Compression:
    """Selector namespace (reference ``torch/compression.py:66-73``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
