"""Functional collective ops on ``torch.Tensor`` values.

This is the torch face of the TPU-native collective engine (reference
``horovod/torch/mpi_ops.py``): tensors are bridged to host arrays, the
collective executes as an XLA collective over the device mesh (or the
cross-process host path when launched multi-process), and the result is
copied back into a torch tensor. Sync, async (handle-based), and in-place
spellings mirror the reference; ``allreduce``/``allgather``/``broadcast``
on ``requires_grad`` tensors are differentiable via autograd Functions
(reference ``torch/mpi_ops.py:162-240``).
"""

from __future__ import annotations

import numpy as np
import torch

from horovod_tpu import basics
from horovod_tpu.ops import collective as C
from horovod_tpu.ops.collective import Adasum, Average, ReduceOp, Sum
from horovod_tpu.torch.compression import Compression

__all__ = [
    "Average", "Sum", "Adasum", "ReduceOp",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_",
    "allgather", "allgather_async",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async",
    "synchronize", "poll", "join",
]


def _to_np(t: torch.Tensor) -> np.ndarray:
    return t.detach().cpu().contiguous().numpy()


def _to_torch(a, like: torch.Tensor) -> torch.Tensor:
    # copy: jax hands back read-only host buffers, torch wants writable
    out = torch.from_numpy(np.array(a, copy=True))
    return out.to(dtype=like.dtype, device=like.device)


class TorchHandle:
    """Async handle (reference ``torch/handle_manager.{h,cc}`` +
    ``torch/mpi_ops.py:475-524``). Wraps the engine handle and converts the
    result back to torch on ``wait``; for in-place ops, copies into the
    original tensor."""

    __slots__ = ("_inner", "_like", "_output", "_post", "_result")

    def __init__(self, inner, like, output=None, post=None):
        self._inner = inner
        self._like = like
        self._output = output
        self._post = post
        self._result = None

    def done(self) -> bool:
        if self._result is not None:
            return True
        try:
            return self._inner.done()
        except AttributeError:  # pragma: no cover
            return True

    def wait(self) -> torch.Tensor:
        if self._result is not None:
            return self._result
        out = self._inner.wait()
        t = _to_torch(out, self._like)
        if self._post is not None:
            t = self._post(t)
        if self._output is not None:
            with torch.no_grad():
                self._output.copy_(t)
            t = self._output
        self._result = t
        return t


def synchronize(handle: TorchHandle) -> torch.Tensor:
    """Block until `handle` completes, return its output (reference
    ``torch/mpi_ops.py:491-508``)."""
    return handle.wait()


def poll(handle: TorchHandle) -> bool:
    """Nonblocking completion check (reference ``torch/mpi_ops.py:475-489``)."""
    return handle.done()


def join() -> int:
    """Uneven-data join (reference ``torch/mpi_ops.py:511-524``)."""
    return C.join()


# --------------------------------------------------------------------- sync


def _run_allreduce(np_tensor, op, name):
    return np.asarray(C.allreduce(np_tensor, op, name=name))


class _AllreduceFn(torch.autograd.Function):
    """Differentiable allreduce: the gradient of an allreduce is the same
    allreduce of the upstream gradient (reference ``torch/mpi_ops.py:162-174``
    ``HorovodAllreduce``)."""

    @staticmethod
    def forward(ctx, tensor, op, name):
        ctx.op = op
        return _to_torch(_run_allreduce(_to_np(tensor), op, name), tensor)

    @staticmethod
    def backward(ctx, grad_output):
        g = _to_torch(
            _run_allreduce(_to_np(grad_output), ctx.op, None), grad_output
        )
        return g, None, None


def allreduce(tensor, average=None, name=None, compression=Compression.none,
              op=None):
    """Averaged (or summed / Adasum-combined) tensor across ranks
    (reference ``torch/mpi_ops.py:182-240``). Differentiable."""
    op = C.handle_average_backwards_compatibility(op, average)
    compressed, ctx = compression.compress(tensor)
    if compressed.requires_grad:
        out = _AllreduceFn.apply(compressed, op, name)
    else:
        out = _to_torch(_run_allreduce(_to_np(compressed), op, name),
                        compressed)
    return compression.decompress(out, ctx)


def allreduce_(tensor, average=None, name=None, op=None):
    """In-place allreduce (reference ``torch/mpi_ops.py:243-263``)."""
    op = C.handle_average_backwards_compatibility(op, average)
    out = _run_allreduce(_to_np(tensor), op, name)
    with torch.no_grad():
        tensor.copy_(_to_torch(out, tensor))
    return tensor


def grouped_allreduce(tensors, average=None, name=None, op=None):
    """One fused collective over a list of tensors (reference grouped path;
    fusion semantics ``controller.cc:640-761``)."""
    op = C.handle_average_backwards_compatibility(op, average)
    outs = C.grouped_allreduce([_to_np(t) for t in tensors], op, name=name)
    return [_to_torch(o, t) for o, t in zip(outs, tensors)]


def grouped_allreduce_(tensors, average=None, name=None, op=None):
    outs = grouped_allreduce(tensors, average=average, name=name, op=op)
    with torch.no_grad():
        for t, o in zip(tensors, outs):
            t.copy_(o)
    return tensors


class _AllgatherFn(torch.autograd.Function):
    """Differentiable allgather: backward allreduce-sums the upstream gradient
    and takes this rank's row slice (reference ``torch/mpi_ops.py:299-312``
    ``HorovodAllgather``)."""

    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim0 = tensor.shape[0]
        return _to_torch(np.asarray(C.allgather(_to_np(tensor), name=name)),
                         tensor)

    @staticmethod
    def backward(ctx, grad_output):
        summed = _to_torch(
            np.asarray(C.allreduce(_to_np(grad_output), Sum)), grad_output
        )
        r = basics.rank()
        return summed[r * ctx.dim0:(r + 1) * ctx.dim0], None


def allgather(tensor, name=None):
    """Concatenate every rank's tensor along dim 0 (reference
    ``torch/mpi_ops.py:271-297``). Differentiable."""
    if tensor.requires_grad:
        return _AllgatherFn.apply(tensor, name)
    return _to_torch(np.asarray(C.allgather(_to_np(tensor), name=name)),
                     tensor)


class _BroadcastFn(torch.autograd.Function):
    """Differentiable broadcast: backward allreduce-sums the gradient to the
    root; non-root ranks get zero (reference ``torch/mpi_ops.py:357-371``
    ``HorovodBroadcast``)."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return _to_torch(
            np.asarray(C.broadcast(_to_np(tensor), root_rank, name=name)),
            tensor,
        )

    @staticmethod
    def backward(ctx, grad_output):
        summed = _to_torch(
            np.asarray(C.allreduce(_to_np(grad_output), Sum)), grad_output
        )
        if basics.rank() != ctx.root_rank:
            summed = torch.zeros_like(summed)
        return summed, None, None


def broadcast(tensor, root_rank, name=None):
    """Tensor from `root_rank` on every rank (reference
    ``torch/mpi_ops.py:329-355``). Differentiable."""
    if tensor.requires_grad:
        return _BroadcastFn.apply(tensor, root_rank, name)
    return _to_torch(
        np.asarray(C.broadcast(_to_np(tensor), root_rank, name=name)), tensor
    )


def broadcast_(tensor, root_rank, name=None):
    """In-place broadcast (reference ``torch/mpi_ops.py:374-394``)."""
    out = np.asarray(C.broadcast(_to_np(tensor), root_rank, name=name))
    with torch.no_grad():
        tensor.copy_(_to_torch(out, tensor))
    return tensor


def alltoall(tensor, name=None):
    """Scatter dim-0 slices to every rank, gather theirs (TPU extension; the
    reference gained alltoall in 0.20)."""
    return _to_torch(np.asarray(C.alltoall(_to_np(tensor), name=name)), tensor)


def reducescatter(tensor, average=None, name=None, op=None):
    """Reduce across ranks, scatter dim-0 blocks (TPU extension; the
    reference gained reducescatter in 0.27)."""
    op = C.handle_average_backwards_compatibility(op, average)
    return _to_torch(
        np.asarray(C.reducescatter(_to_np(tensor), op, name=name)), tensor
    )


# -------------------------------------------------------------------- async


def allreduce_async(tensor, average=None, name=None, op=None):
    """Handle-returning allreduce (reference ``torch/mpi_ops.py:94-129``)."""
    op = C.handle_average_backwards_compatibility(op, average)
    inner = C.allreduce_async(_to_np(tensor), op, name=name)
    return TorchHandle(inner, tensor)


def allreduce_async_(tensor, average=None, name=None, op=None):
    """In-place async allreduce: on ``synchronize`` the result is copied back
    into `tensor` (reference ``torch/mpi_ops.py:243-268``)."""
    op = C.handle_average_backwards_compatibility(op, average)
    inner = C.allreduce_async(_to_np(tensor), op, name=name)
    return TorchHandle(inner, tensor, output=tensor)


def allgather_async(tensor, name=None):
    inner = C.allgather_async(_to_np(tensor), name=name)
    return TorchHandle(inner, tensor)


def broadcast_async(tensor, root_rank, name=None):
    inner = C.broadcast_async(_to_np(tensor), root_rank, name=name)
    return TorchHandle(inner, tensor)


def broadcast_async_(tensor, root_rank, name=None):
    inner = C.broadcast_async(_to_np(tensor), root_rank, name=name)
    return TorchHandle(inner, tensor, output=tensor)


def alltoall_async(tensor, name=None):
    inner = C.alltoall_async(_to_np(tensor), name=name)
    return TorchHandle(inner, tensor)


def reducescatter_async(tensor, average=None, name=None, op=None):
    op = C.handle_average_backwards_compatibility(op, average)
    inner = C.reducescatter_async(_to_np(tensor), op, name=name)
    return TorchHandle(inner, tensor)
