"""Process/mesh bootstrap and identity queries.

TPU-native analog of Horovod's ``HorovodBasics`` (reference
``horovod/common/basics.py:22-131`` + the C side ``horovod_init/_rank/_size/...``
``horovod/common/operations.cc:661-799``).

Identity model
--------------
Horovod runs one process per accelerator; ``rank`` is the process index. On TPU
the natural unit of data parallelism is the *chip*, and a single process owns
several chips (or, single-controller, all of them). We therefore define:

- ``size()``    — number of mesh slices along the **data axis** (the DP degree);
                  equals total chips for the default 1-D mesh. This is what
                  Horovod calls ``size`` (``basics.py:100-106``).
- ``rank()``    — data-axis coordinate of this process's first local device.
                  Single-controller: always 0. Multi-host process-major meshes:
                  process_index * chips_per_process, matching Horovod's
                  rank-major allocation (``run/gloo_run.py:54-112``).
- ``local_size()/local_rank()`` — processes on this host / this process's
  slot index, from launcher env when exported (Horovod ``basics.py:108-122``);
  single-process default: chips owned / 0. ``local_chip_count()`` is always
  the chips-owned figure (hostlocal tiling).
- ``cross_rank()/cross_size()`` — host-level coordinates (Horovod's CROSS
  communicator, ``common/common.h:111-115``).

Build/feature queries (`*_built`) mirror ``horovod_*_built`` in
``operations.cc:713-746``: the only data-plane backend here is XLA.
"""

from __future__ import annotations

import atexit
import dataclasses
import logging
import os
import threading
from typing import Optional, Sequence

import jax
import numpy as np

from horovod_tpu.parallel.mesh import build_mesh, DATA_AXIS

logger = logging.getLogger("horovod_tpu")


@dataclasses.dataclass
class _GlobalState:
    """Python-side analog of HorovodGlobalState (reference
    ``horovod/common/global_state.h:42-122``). Device-side state (fusion
    buffers) lives in the core/ops modules; control-plane state (tensor queue,
    controller) lives in the native core once attached."""

    initialized: bool = False
    mesh: Optional[jax.sharding.Mesh] = None
    #: mesh of the previous init, kept across shutdown: a re-init whose
    #: mesh differs (elastic resize) must drop the compiled-eager-kernel
    #: caches keyed by the old one; a re-init on the SAME mesh keeps them
    #: (meshes over identical devices/axes compare equal — the caches are
    #: warm hits, and clearing would recompile every eager collective)
    prev_mesh: Optional[jax.sharding.Mesh] = None
    #: axis name, or a (cross, local) tuple on host-hierarchy meshes
    data_axis: "str | tuple" = DATA_AXIS
    # process-level identity (multi-host)
    process_index: int = 0
    process_count: int = 1
    local_device_count: int = 0
    local_process_rank: int = 0
    local_slot_count: int = 0  # launcher slots on this host (0 = not launched)
    homogeneous: bool = True
    lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)
    core: object = None  # native core handle (attached by horovod_tpu.core)


_state = _GlobalState()
_atexit_registered = False


def init(
    mesh: Optional[jax.sharding.Mesh] = None,
    *,
    axes: Optional[dict] = None,
    devices: Optional[Sequence] = None,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    comm=None,
    native_core: Optional[bool] = None,
) -> None:
    """Initialize the framework. Analog of ``hvd.init()`` (reference
    ``horovod/common/basics.py:33-65`` -> ``operations.cc:604-650``).

    Where Horovod spawns the C++ background negotiation thread and rendezvouses
    via Gloo/MPI, we (a) optionally wire up multi-host JAX via
    ``jax.distributed.initialize`` (the TPU-native rendezvous; coordinates read
    from args or ``HVD_COORDINATOR_ADDR``/``HVD_NUM_PROCESSES``/``HVD_PROCESS_ID``
    env set by the launcher, mirroring ``HOROVOD_GLOO_RENDEZVOUS_ADDR`` et al.,
    reference ``run/gloo_run.py:152-163``), and (b) build the device mesh that
    every collective lowers onto.

    Args:
      mesh: pre-built ``jax.sharding.Mesh`` to adopt. Must contain the data
        axis (default ``"data"``).
      axes: mesh axes spec passed to :func:`build_mesh`, e.g.
        ``{"data": -1}`` (default) or ``{"data": -1, "model": 4}``.
      devices: subset of devices to use (Horovod's ``init(ranks)`` subset,
        ``basics.py:33-42``).
      coordinator_address/num_processes/process_id: multi-host wire-up.
      comm: unsupported (MPI communicator in the reference); raises if not None.
    """
    # HOROVOD_XLA_FLAGS_PRESET: arm the async-collective/latency-hiding
    # XLA flags BEFORE the first backend touch below (XLA reads XLA_FLAGS
    # exactly once, at backend creation) — the env-knob spelling of
    # horovod_tpu.tuning.apply_xla_flags, a no-op when unset
    from horovod_tpu import tuning as _tuning

    _tuning.maybe_apply_from_env()
    if comm is not None:
        if not isinstance(comm, (list, tuple)):
            raise ValueError(
                "horovod_tpu does not speak MPI; pass a device subset via "
                "`devices=`/`comm=[ranks]` or a prebuilt `mesh=` instead of "
                "an MPI communicator."
            )
        # reference init(ranks) subset (basics.py:33-42): rank i -> chip i
        if devices is not None:
            raise ValueError("pass either `comm` (rank subset) or `devices`")
        all_devices = jax.devices()
        devices = [all_devices[i] for i in comm]
    with _state.lock:
        if _state.initialized:
            return

        from horovod_tpu import compat

        compat.warn_if_unsupported()

        coord = coordinator_address or os.environ.get("HVD_COORDINATOR_ADDR")
        nproc = num_processes or _env_int("HVD_NUM_PROCESSES")
        pid = process_id if process_id is not None else _env_int("HVD_PROCESS_ID")
        if coord and nproc and nproc > 1:
            # Must run before anything initializes the XLA backend (so no
            # jax.process_count() guard here — that call itself would
            # initialize the backend and make this fail).
            try:
                # CPU multi-process needs gloo collectives to federate device
                # views across processes (TPU runtimes federate natively; the
                # flag only affects CPU-client creation, so set it whenever
                # multi-process — the default platform may resolve to cpu).
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception as e:
                import logging

                logging.getLogger("horovod_tpu").warning(
                    "could not enable gloo CPU collectives (%s); "
                    "multi-process CPU collectives may fail", e
                )
            try:
                kw = {}
                start_timeout = _env_int("HVD_START_TIMEOUT")
                if start_timeout:
                    kw["initialization_timeout"] = start_timeout
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=nproc,
                    process_id=pid or 0,
                    **kw,
                )
            except RuntimeError as e:  # already initialized by the caller
                if "already" not in str(e).lower():
                    raise

        if mesh is not None and axes is not None:
            raise ValueError("pass either `mesh` or `axes`, not both")
        if mesh is None:
            mesh = build_mesh(axes=axes, devices=devices)
        if _state.prev_mesh is not None and _state.prev_mesh != mesh:
            # live-process re-init onto a DIFFERENT mesh (elastic resize):
            # the compiled-eager-kernel caches are keyed by the old mesh —
            # unreachable hits that pin stale programs and device buffers
            try:
                from horovod_tpu.ops import collective as _C

                _C.clear_eager_caches()
            except Exception as e:
                logger.debug("eager-cache clear on re-init failed: %s", e)
        _state.prev_mesh = mesh
        _state.mesh = mesh
        from horovod_tpu.parallel.mesh import CROSS_AXIS, LOCAL_AXIS

        if DATA_AXIS in mesh.axis_names:
            _state.data_axis = DATA_AXIS
        elif {CROSS_AXIS, LOCAL_AXIS} <= set(mesh.axis_names):
            # host-hierarchy mesh: the Horovod GLOBAL communicator is BOTH
            # axes — defaulting to just one would silently reduce over
            # hosts (or chips) only
            _state.data_axis = (CROSS_AXIS, LOCAL_AXIS)
        else:
            _state.data_axis = mesh.axis_names[0]
        _state.process_index = jax.process_index()
        _state.process_count = jax.process_count()
        _state.local_device_count = len(
            [d for d in mesh.devices.flat if d.process_index == _state.process_index]
        ) or jax.local_device_count()
        counts = _per_process_device_counts(mesh)
        _state.homogeneous = len(set(counts)) <= 1
        # Launcher-assigned slot coordinates within the host: -H host:2 puts
        # two processes on one host, so these cannot be hardwired (reference
        # derives them per slot, ``basics.py:108-122``, ``run/gloo_run.py:54-112``).
        # local_slot_count (HOROVOD_LOCAL_SIZE) is the number of *processes*
        # on this host — distinct from local_device_count (chips owned by
        # this process, which hostlocal tiling uses) — so that
        # local_rank() < local_size() always holds.
        _state.local_process_rank = _env_int("HOROVOD_LOCAL_RANK") or 0
        _state.local_slot_count = _env_int("HOROVOD_LOCAL_SIZE") or 0

        # Optionally attach the native control-plane core (csrc/): named
        # async collectives then go through the background negotiation cycle
        # (tensor fusion, response cache, stall detection, timeline) instead
        # of direct dispatch. Mandatory for multi-process named ops.
        use_core = native_core
        if use_core is None:
            use_core = os.environ.get("HOROVOD_NATIVE_CORE", "0") == "1"
        if use_core:
            from horovod_tpu.core import NativeCore

            _state.core = NativeCore(
                rank=_state.process_index,
                size=_state.process_count,
                coordinator_host=os.environ.get("HVD_CORE_COORD_ADDR"),
                coordinator_port=int(
                    os.environ.get("HVD_CORE_COORD_PORT", "29500")
                ),
            )
        _state.initialized = True

        # Opt-in metrics endpoint (HOROVOD_METRICS_PORT), rank 0 only —
        # the same coordinator-only convention as the reference Timeline.
        # Never let observability take down init.
        try:
            from horovod_tpu.observability import exporters, trace

            # every rank records for the fleet merge (the span ring bounds
            # memory; ranks != 0 flush to a per-rank sidecar at shutdown).
            # HOROVOD_TRACE_ALL_RANKS=0 restores the PR-1 coordinator-only
            # mode: ranks != 0 never record (no append cost, no sidecar).
            all_ranks = os.environ.get(
                "HOROVOD_TRACE_ALL_RANKS", "1"
            ).lower() not in ("0", "false")
            trace.set_recording(_state.process_index == 0 or all_ranks)
            if _state.process_index == 0:
                exporters.maybe_start_http_server()
            # hang watchdog: armed iff HOROVOD_HANG_TIMEOUT > 0 (the
            # flight ring itself is always-on and needs no arming)
            from horovod_tpu.observability import flight

            flight.maybe_arm_watchdog()
        except Exception as e:
            # observability must never take down init — but it should
            # say why it is missing
            logger.debug("observability bring-up skipped: %s", e)
    global _atexit_registered
    if not _atexit_registered:
        # once per process, not once per init: a shutdown() → init() cycle
        # (elastic re-init) must not stack a new atexit entry each
        # generation — the old handles would otherwise accumulate forever
        atexit.register(shutdown)
        _atexit_registered = True


def flush_timeline() -> None:
    """Flush the host trace ring: process rank 0 merges into the
    ``HOROVOD_TIMELINE`` file the native core wrote; every other rank
    writes its per-rank sidecar (``<HOROVOD_TIMELINE>.rank<r>.json``) for
    the skew-corrected fleet merge. Shared by :func:`shutdown` and the
    SIGTERM drain in :mod:`horovod_tpu.resilience.loop` — a preempted run
    must keep its spans, not only its weights."""
    from horovod_tpu.observability import trace

    idx = _state.process_index
    if idx == 0:
        trace.flush()
    else:
        base = os.environ.get("HOROVOD_TIMELINE")
        if base:
            trace.flush(f"{base}.rank{idx}.json")


def shutdown() -> None:
    """Analog of ``hvd.shutdown()`` (reference ``basics.py:67-73``).

    Safe to follow with a fresh :func:`init` on the same live process (the
    elastic world-size path re-forms the mesh this way): the native core
    handle is released and the outstanding-collective name set is cleared
    (an async op left in flight at death must not poison the next init
    with DUPLICATE_NAME). The compiled-eager-kernel caches survive — a
    re-init on an equal mesh reuses them warm; :func:`init` drops them
    only when the new mesh actually differs (elastic resize).
    """
    with _state.lock:
        if not _state.initialized:
            return
        if _state.core is not None:
            try:
                _state.core.shutdown()
            except Exception as e:
                logger.debug("native core shutdown failed: %s", e)
            _state.core = None
        # Merge buffered host spans into the (now closed) native timeline
        # file — rank 0, the rank whose file the core wrote; every other
        # rank flushes its buffer to a per-rank sidecar
        # (<HOROVOD_TIMELINE>.rank<r>.json) for the skew-corrected fleet
        # merge (observability.clock.merge_rank_traces).
        try:
            flush_timeline()
        except Exception as e:
            logger.debug("timeline flush at shutdown failed: %s", e)
        # flight ring: disarm the hang watchdog (a re-init re-arms it for
        # the new generation) and push any pending events to the sidecar
        try:
            from horovod_tpu.observability import flight

            flight.disarm_watchdog()
            flight.flush()
        except Exception as e:
            logger.debug("flight flush at shutdown failed: %s", e)
        # The LAST step's schedule record only publishes at the next step
        # boundary — which never comes. Flush it here so a divergence at
        # the final step (the crash-adjacent case) is still named.
        try:
            from horovod_tpu.analysis import sanitizer as _sanitize

            _sanitize.flush()
        except Exception as e:
            logger.debug("sanitizer flush at shutdown failed: %s", e)
        # same last-boundary problem for the numerics guard's lagged
        # standalone verdict: the final step has no next boundary
        try:
            from horovod_tpu.resilience import numerics as _numerics

            _numerics.flush_staged()
        except Exception as e:
            logger.debug("numerics flush at shutdown failed: %s", e)
        try:
            from horovod_tpu.ops import collective as _C

            _C.clear_outstanding_names()
        except Exception as e:
            logger.debug("outstanding-name clear at shutdown failed: %s", e)
        _state.mesh = None
        _state.initialized = False


def is_initialized() -> bool:
    return _state.initialized


def _require_init() -> _GlobalState:
    if not _state.initialized:
        # Horovod raises "Horovod has not been initialized; use hvd.init()."
        # (common/operations.cc checks initialization_done).
        raise RuntimeError(
            "horovod_tpu has not been initialized; call horovod_tpu.init() first."
        )
    return _state


def mesh() -> jax.sharding.Mesh:
    """The global device mesh all collectives run over."""
    return _require_init().mesh


def core():
    """The attached native control-plane core, or None when running without
    it (``init(native_core=True)`` / ``hvdrun --native-core`` attach it)."""
    return _require_init().core


def data_axis() -> "str | tuple":
    """Name of the data-parallel mesh axis."""
    return _require_init().data_axis


def size() -> int:
    """DP degree: chips along the data axis (Horovod ``size()``). On a
    host-hierarchy mesh the data axis is the ``(cross, local)`` pair and
    size() is their product — the GLOBAL communicator size."""
    st = _require_init()
    if isinstance(st.data_axis, tuple):
        n = 1
        for a in st.data_axis:
            n *= st.mesh.shape[a]
        return n
    return st.mesh.shape[st.data_axis]


def rank() -> int:
    """Data-axis coordinate of this process's first local device."""
    st = _require_init()
    if st.process_count == 1:
        return 0
    devs = st.mesh.devices
    names = st.mesh.axis_names
    axes = st.data_axis if isinstance(st.data_axis, tuple) else (st.data_axis,)
    coords = np.argwhere(
        np.vectorize(lambda d: d.process_index)(devs) == st.process_index
    )
    if coords.size == 0:
        return 0
    # row-major flatten of each local device's (possibly multi-axis) data
    # coordinate; report the smallest (the process's first device)
    idxs = [names.index(a) for a in axes]
    best = None
    for row in coords:
        r = 0
        for a, i in zip(axes, idxs):
            r = r * st.mesh.shape[a] + int(row[i])
        best = r if best is None else min(best, r)
    return best


def local_size() -> int:
    """Processes on this host when the launcher exported slot coordinates
    (HOROVOD_LOCAL_SIZE); otherwise chips owned by this process (the
    TPU-native unit when one process spans a host's chips). Either way
    ``local_rank() < local_size()`` holds (reference ``basics.py:108-122``)."""
    st = _require_init()
    return st.local_slot_count or st.local_device_count


def local_chip_count() -> int:
    """Chips this process owns on the mesh — the hostlocal tiling factor.
    Distinct from :func:`local_size` under multi-slot launches (two
    one-chip processes on a host: local_size()==2, local_chip_count()==1)."""
    return _require_init().local_device_count


def local_rank() -> int:
    """Index of this process within its host's processes (reference
    ``basics.py:108-122``). 0 in the one-process-per-host TPU-native layout;
    the launcher exports ``HOROVOD_LOCAL_RANK`` per slot
    (:func:`horovod_tpu.run.hosts.slot_env`) so ``-H host:2`` style
    multi-slot hosts get distinct values."""
    return _require_init().local_process_rank


def cross_rank() -> int:
    return _require_init().process_index


def cross_size() -> int:
    return _require_init().process_count


def process_rank() -> int:
    return _require_init().process_index


def process_size() -> int:
    return _require_init().process_count


def is_homogeneous() -> bool:
    """All processes own the same number of chips (reference
    ``mpi_controller.cc:25-81`` homogeneity check)."""
    return _require_init().homogeneous


# --- health (resilience state machine) -------------------------------------


def health_state():
    """This process's :class:`~horovod_tpu.resilience.HealthState`
    (``HEALTHY → SUSPECT → DEGRADED → FATAL``), fed by the native core's
    cycle/stall signals and the retry layer. Readable before :func:`init`
    (always ``HEALTHY`` until something feeds the monitor)."""
    from horovod_tpu.resilience import health as _health

    return _health.health_state()


def health() -> dict:
    """JSON-able health snapshot (state, reason, strike count, last-beat
    age) — what the rank-0 metrics endpoint serves at ``/health``."""
    from horovod_tpu.resilience import health as _health

    return _health.snapshot()


# --- build/feature queries (reference operations.cc:713-760) ---------------


def mpi_threads_supported() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def xla_built() -> bool:
    """The one true data plane here."""
    return True


def num_rank_is_power_2(num: int) -> bool:
    """Reference ``common/util.py:163-171`` — the Adasum precondition check
    user scripts call before opting into ``op=hvd.Adasum``."""
    return num != 0 and (num & (num - 1)) == 0


def gpu_available(ext_base_name: str = None, verbose: bool = False) -> bool:
    """Reference ``common/util.py:125-128`` compat shim: is a GPU driving
    this job? Never — the accelerator here is TPU (query
    ``jax.devices()[0].device_kind`` for what is actually attached)."""
    del ext_base_name, verbose
    return False


def mpi_enabled() -> bool:
    """Runtime controller query (reference ``basics.py:151-160``): is MPI
    driving coordination? Never — no MPI exists here by design."""
    return False


def gloo_enabled() -> bool:
    """Runtime controller query (reference ``basics.py:170-179``). The TCP
    controller + KV rendezvous fill the role the reference calls gloo mode
    (its no-MPI configuration), so this answers True — consistent with
    ``hvdrun --gloo`` being an accepted no-op."""
    return True


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v else None


def _per_process_device_counts(mesh: jax.sharding.Mesh):
    counts = {}
    for d in mesh.devices.flat:
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    return list(counts.values())
