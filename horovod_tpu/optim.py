"""Distributed optimizer wrappers.

TPU-native analog of Horovod's ``DistributedOptimizer`` /
``DistributedGradientTape`` (reference ``horovod/tensorflow/__init__.py:270-535``,
``horovod/torch/__init__.py:67-222``): wrap a local optimizer so gradients are
averaged across the data axis before being applied. Here the local optimizer is
an ``optax.GradientTransformation`` and the allreduce lowers to ``lax.pmean``
inside the jitted step (XLA overlaps it with the backward pass, the role
Horovod's background cycle + fusion buffer play in the reference).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import optax

from horovod_tpu import basics
from horovod_tpu.compression import Compression
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.ops.collective import (
    Average,
    Adasum,
    ReduceOp,
    Sum,
    allreduce,
    broadcast,
    broadcast_object,
)


def _fused_adasum_tree(grads, axis):
    """Adasum the whole gradient tree through the fused group butterfly —
    log2(ranks) collectives total (ops/adasum.py). Only for uncompressed
    gradients: the fused flat buffer is fp32, so compressing into it would
    add rounding error while saving zero wire bandwidth; compressed Adasum
    stays per-leaf where the 16-bit dtype rides end-to-end."""
    from horovod_tpu.ops.adasum import grouped_adasum_allreduce

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    return jax.tree_util.tree_unflatten(
        treedef, grouped_adasum_allreduce(leaves, axis=axis)
    )


class _EFState(NamedTuple):
    """State for error-feedback compression: the inner optimizer's state plus
    the per-rank residual tree (what lossy compression rounded away so far)."""

    inner: Any
    residual: Any


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    op: ReduceOp = Average,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    axis: Optional[str] = None,
    gradient_predivide_factor: float = 1.0,
    error_feedback: bool = False,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so each ``update`` first allreduces gradients
    across ranks (reference ``_DistributedOptimizer.compute_gradients``,
    ``tensorflow/__init__.py:270-315``; torch hook-based variant
    ``torch/__init__.py:67-222``).

    ``backward_passes_per_step > 1`` accumulates that many gradient
    applications locally before communicating (reference
    ``torch/__init__.py:72-96``) via ``optax.MultiSteps``.

    ``gradient_predivide_factor`` splits the averaging divisor between
    pre/post-scale as the reference does for numerical headroom
    (upstream semantics: pre-divide by f, post-divide by size/f).

    ``error_feedback=True`` (beyond the reference; EF-SGD, Karimireddy et
    al. 2019) makes lossy ``compression`` convergence-safe: each rank keeps
    the rounding error the compressor discarded and adds it back into the
    next step's gradient, so systematic bias (components smaller than a
    bfloat16 ULP vanishing every step) accumulates until it transmits
    instead of being lost. All elementwise — XLA fuses it into the step.
    Requires a lossy compressor; pair with Average/Sum (Adasum's scalar
    projections would mix into the residual bookkeeping).
    """
    if error_feedback and compression is Compression.none:
        raise ValueError(
            "error_feedback=True needs a lossy compression "
            "(e.g. Compression.fp16); with Compression.none there is no "
            "rounding error to feed back"
        )
    if error_feedback and op == Adasum:
        raise ValueError("error_feedback is not supported with op=Adasum")

    def _allreduce_grads(grads):
        if op == Adasum and compression is Compression.none:
            return _fused_adasum_tree(grads, axis)

        def one(g):
            if op == Average and gradient_predivide_factor != 1.0:
                g = g / gradient_predivide_factor
                out = allreduce(g, Sum, axis=axis, compression=compression)
                return out * (gradient_predivide_factor / basics.size())
            return allreduce(g, op, axis=axis, compression=compression)

        return jax.tree_util.tree_map(one, grads)

    def _roundtrip(g):
        """The value g effectively contributes through the wire. With a
        predivide the wire carries compress(g/f) (scaled back by f at the
        receiver), so the residual must be measured against THAT — rounding
        introduced by the divide is exactly the bias EF exists to track."""
        if op == Average and gradient_predivide_factor != 1.0:
            c, ctx = compression.compress(g / gradient_predivide_factor)
            return compression.decompress(c, ctx) * gradient_predivide_factor
        c, ctx = compression.compress(g)
        return compression.decompress(c, ctx)

    def init_fn(params):
        inner = optimizer.init(params)
        if error_feedback:
            residual = jax.tree_util.tree_map(jax.numpy.zeros_like, params)
            return _EFState(inner, residual)
        return inner

    def update_fn(grads, state, params=None, **extra):
        if error_feedback:
            corrected = jax.tree_util.tree_map(
                lambda g, r: g + r, grads, state.residual
            )
            # residual = what the wire will round away; the allreduce below
            # compresses `corrected` itself (single compression pass), which
            # is exactly the transform _roundtrip models
            residual = jax.tree_util.tree_map(
                lambda c: c - _roundtrip(c), corrected
            )
            reduced = _allreduce_grads(corrected)
            updates, inner = optimizer.update(
                reduced, state.inner, params, **extra
            )
            return updates, _EFState(inner, residual)
        grads = _allreduce_grads(grads)
        return optimizer.update(grads, state, params, **extra)

    tx = optax.GradientTransformationExtraArgs(init_fn, update_fn)
    if backward_passes_per_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)
    return tx


class DistributedGradientTape:
    """Analog of ``hvd.DistributedGradientTape`` (reference
    ``tensorflow/__init__.py:478-535``): wraps a gradient-producing function
    (e.g. ``jax.grad(loss)`` or ``jax.value_and_grad(loss)``) so its gradients
    are allreduced.

    Example::

        tape = hvd.DistributedGradientTape(jax.value_and_grad(loss_fn))
        (loss, grads) = tape(params, batch)   # grads are rank-averaged
    """

    def __init__(
        self,
        grad_fn: Callable,
        *,
        op: ReduceOp = Average,
        compression=Compression.none,
        axis: Optional[str] = None,
        has_aux_value: Optional[bool] = None,
    ):
        self._fn = grad_fn
        self._op = op
        self._compression = compression
        self._axis = axis
        self._has_aux_value = has_aux_value

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        has_value = self._has_aux_value
        if has_value is None:
            # value_and_grad returns (scalar_loss, grads). Require the first
            # element to actually look like a scalar loss so a 2-tuple of
            # gradients (jax.grad with argnums=(0, 1)) is not misclassified;
            # pass has_aux_value explicitly for ambiguous cases.
            has_value = (
                isinstance(out, tuple)
                and len(out) == 2
                and not isinstance(out[0], (list, dict))
                and getattr(out[0], "ndim", None) == 0
            )
        if has_value:
            value, grads = out
        else:
            grads = out
        if self._op == Adasum and self._compression is Compression.none:
            grads = _fused_adasum_tree(grads, self._axis)
        else:
            grads = jax.tree_util.tree_map(
                lambda g: allreduce(
                    g, self._op, axis=self._axis,
                    compression=self._compression,
                ),
                grads,
            )
        self._record(grads)
        return (value, grads) if has_value else grads

    @staticmethod
    def _record(grads):
        """Per-step accounting for the tape path. Eager calls only: under
        jit this __call__ body runs once at trace time, so recording there
        would freeze a single count into the compiled step."""
        if not _metrics.enabled():
            return
        leaves = jax.tree_util.tree_leaves(grads)
        if any(isinstance(g, jax.core.Tracer) for g in leaves):
            return
        _metrics.counter(
            "tape_steps", help="DistributedGradientTape gradient exchanges"
        ).inc()
        _metrics.counter(
            "tape_grad_bytes", help="gradient bytes exchanged by the tape"
        ).inc(sum(getattr(g, "nbytes", 0) or 0 for g in leaves))


def broadcast_parameters(params: Any, root_rank: int = 0, *, axis=None):
    """Broadcast a pytree of parameters from root (reference
    ``torch/__init__.py:451-469``, ``tensorflow/__init__.py:126-152``
    ``broadcast_variables``). Under single-controller SPMD parameters are
    born synchronized; this is the multi-process resync primitive and the
    checkpoint-restore pattern (SURVEY.md §5.4)."""
    _metrics.counter(
        "broadcast_parameters_calls",
        help="parameter-tree broadcasts (init sync / checkpoint restore)",
    ).inc()
    return jax.tree_util.tree_map(
        lambda p: broadcast(p, root_rank, axis=axis)
        if isinstance(p, (jax.Array,)) or hasattr(p, "dtype")
        else broadcast_object(p, root_rank),
        params,
    )


broadcast_variables = broadcast_parameters


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0, *, axis=None):
    """Broadcast optimizer state (reference ``torch/__init__.py:471-607``:
    scalars are wrapped into tensors and broadcast; here the optax state is
    already a pytree of arrays/scalars)."""
    return broadcast_parameters(opt_state, root_rank, axis=axis)
