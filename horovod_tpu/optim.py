"""Distributed optimizer wrappers.

TPU-native analog of Horovod's ``DistributedOptimizer`` /
``DistributedGradientTape`` (reference ``horovod/tensorflow/__init__.py:270-535``,
``horovod/torch/__init__.py:67-222``): wrap a local optimizer so gradients are
averaged across the data axis before being applied. Here the local optimizer is
an ``optax.GradientTransformation`` and the allreduce lowers to ``lax.pmean``
inside the jitted step (XLA overlaps it with the backward pass, the role
Horovod's background cycle + fusion buffer play in the reference).
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import basics
from horovod_tpu.compression import (
    Compression,
    Int8Compressor,
    _quantizable,
    int8_roundtrip,
    quantize_chunked,
    quantize_roundtrip_chunked,
)
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.ops import collective as _C
from horovod_tpu.ops import overlap as _ov
from horovod_tpu.ops.collective import (
    Average,
    Adasum,
    ReduceOp,
    Sum,
    allreduce,
    broadcast,
    broadcast_object,
)


def _fused_adasum_tree(grads, axis):
    """Adasum the whole gradient tree through the fused group butterfly —
    log2(ranks) collectives total (ops/adasum.py). Only for uncompressed
    gradients: the fused flat buffer is fp32, so compressing into it would
    add rounding error while saving zero wire bandwidth; compressed Adasum
    stays per-leaf where the 16-bit dtype rides end-to-end."""
    from horovod_tpu.ops.adasum import grouped_adasum_allreduce

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    return jax.tree_util.tree_unflatten(
        treedef, grouped_adasum_allreduce(leaves, axis=axis)
    )


class _EFState(NamedTuple):
    """State for error-feedback compression: the inner optimizer's state plus
    the per-rank residual tree (what lossy compression rounded away so far).

    The sharded (ZeRO-1) path reuses this composition: ``inner`` holds the
    per-rank shard states (every leaf carries a leading rank axis) and
    ``residual`` the per-rank flat residual buffers keyed by dtype — so
    error feedback shards through the same pytree the replicated path uses.
    """

    inner: Any
    residual: Any


class _PowerSGDState(NamedTuple):
    """PowerSGD optimizer state: the inner state, the error-feedback
    residual (param tree replicated, or the per-dtype flat ``[N, Lp]``
    buffers when sharded — the same packing :class:`_EFState` uses), and
    the warm-started ``Q`` factor tree — one ``[m, r]`` matrix per
    factorized (>=2-D float) leaf, ``None`` elsewhere; sharded states tile
    ``Q`` to ``[N, m, r]`` so EVERY leaf keeps the leading rank axis the
    ``shard_map`` specs rely on (the rows are identical by construction:
    ``Q`` comes out of an allreduce)."""

    inner: Any
    residual: Any
    q: Any


def _q_is_leaf(x) -> bool:
    return x is None


def _q_leaves(q_tree):
    """Flatten the Q tree keeping the ``None`` placeholders as leaves, so
    the list stays parallel to the gradient leaves."""
    return jax.tree_util.tree_flatten(q_tree, is_leaf=_q_is_leaf)[0]


def _powersgd_q_init(params, compression, n: Optional[int] = None):
    """Deterministic gaussian ``Q`` per factorized leaf (every rank runs the
    same program, so the seeds agree without a broadcast); ``n`` tiles a
    leading rank axis for the sharded state layout."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    qs = []
    for i, p in enumerate(leaves):
        shape = tuple(getattr(p, "shape", ()))
        if compression.factorizes(shape, _leaf_dtype(p)):
            m = int(np.prod(shape[1:], dtype=np.int64))
            r = compression.effective_rank(shape)
            q = jax.random.normal(
                jax.random.PRNGKey(0x9D5D + i), (m, r), jnp.float32)
            if n is not None:
                q = jnp.broadcast_to(q[None], (n, m, r))
            qs.append(q)
        else:
            qs.append(None)
    return jax.tree_util.tree_unflatten(treedef, qs)


def _orthonormalize(p, eps: float = 1e-8):
    """Single modified Gram-Schmidt pass over the (few, static) columns of
    ``P`` — the one orthogonalization PowerSGD performs per step."""
    cols = []
    for i in range(p.shape[1]):
        v = p[:, i]
        for u in cols:
            v = v - jnp.dot(u, v) * u
        v = v / (jnp.sqrt(jnp.sum(v * v)) + eps)
        cols.append(v)
    return jnp.stack(cols, axis=1)


def _psgd_factor_sync(m2d, qmat, reduce_mean):
    """One PowerSGD round on a 2-D per-rank matrix: ``P = M @ Q`` (mean
    across ranks), orthonormalize, ``Q' = M^T @ P`` (mean across ranks).
    Returns ``(P @ Q'^T, Q')`` — the rank-r approximation of the MEAN
    gradient plus the warm-start factor for the next step. Only the small
    ``P``/``Q'`` factors cross the wire."""
    p = reduce_mean(m2d @ qmat)
    p = _orthonormalize(p)
    qn = reduce_mean(m2d.T @ p)
    return p @ qn.T, qn


def _pallas_on() -> bool:
    from horovod_tpu.ops import pallas_kernels as _pk

    return _pk.enabled()


def fused_adam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8, eps_root: float = 0.0):
    """Adam as a single fused Pallas kernel per (bucket) shard: moment
    update + bias correction + parameter step in one VMEM pass
    (:func:`horovod_tpu.ops.pallas_kernels.fused_adam_update`), instead
    of the ~10 elementwise HLO ops of ``optax.adam``.

    Drop-in for ``optax.adam`` as the inner optimizer of
    :class:`DistributedOptimizer` — the state pytree IS
    ``optax.adam``'s (``(ScaleByAdamState, EmptyState)``), so
    checkpoints are interchangeable across ``HOROVOD_PALLAS=0/1`` (the
    save→restore bit-stability the acceptance pins) and the ZeRO-1
    ``[N, shard_k]`` per-bucket state layout, ``reshard_optimizer_state``
    and ``broadcast_optimizer_state`` all behave identically. With the
    knob off (or on non-TPU backends under ``auto``) the update IS
    ``optax.adam``'s, bit for bit; with it on, the fused kernel mirrors
    the optax expressions exactly (interpret mode pins ≤1 ULP).

    The fused kernel composes with ``shard_optimizer=True``'s vmapped
    per-bucket update — under ``jax.vmap`` the Pallas call batches over
    the ``[N, shard_k]`` rank axis, one VMEM-resident bucket per
    invocation. Only static float learning rates are supported (a
    schedule would re-introduce the host-side count dependence the
    kernel folds in)."""
    if callable(learning_rate):
        raise ValueError(
            "fused_adam requires a static float learning_rate; wrap an "
            "optax schedule around optax.adam instead"
        )
    lr = float(learning_rate)
    ref = optax.adam(lr, b1=b1, b2=b2, eps=eps, eps_root=eps_root)

    def init_fn(params):
        return ref.init(params)

    def update_fn(updates, state, params=None):
        from horovod_tpu.ops import pallas_kernels as _pk

        if not _pk.enabled():
            return ref.update(updates, state, params)
        adam_st = state[0]
        count_inc = optax.safe_int32_increment(adam_st.count)
        # the traced bias corrections — the exact optax expressions
        b1c = 1 - b1 ** count_inc
        b2c = 1 - b2 ** count_inc
        g_leaves, treedef = jax.tree_util.tree_flatten(updates)
        mu_leaves = jax.tree_util.tree_leaves(adam_st.mu)
        nu_leaves = jax.tree_util.tree_leaves(adam_st.nu)
        us, mus, nus = [], [], []
        for g, m, v in zip(g_leaves, mu_leaves, nu_leaves):
            shape = tuple(g.shape)
            u1, m1, v1 = _pk.fused_adam_update(
                g.reshape(-1), m.reshape(-1), v.reshape(-1), b1c, b2c,
                lr=lr, b1=b1, b2=b2, eps=eps, eps_root=eps_root)
            us.append(u1.reshape(shape))
            mus.append(m1.reshape(shape))
            nus.append(v1.reshape(shape))
        new_adam = optax.ScaleByAdamState(
            count=count_inc,
            mu=jax.tree_util.tree_unflatten(treedef, mus),
            nu=jax.tree_util.tree_unflatten(treedef, nus),
        )
        return (
            jax.tree_util.tree_unflatten(treedef, us),
            (new_adam,) + tuple(state[1:]),
        )

    return optax.GradientTransformation(init_fn, update_fn)


# --------------------------------------------------------------------------
# ZeRO-1: sharded gradient sync + sharded optimizer state
#
# The reference (and the replicated path above) allreduces every gradient —
# ring cost 2(N-1)/N·B — and redundantly runs the full optimizer update on
# every rank. The sharded path decomposes the exchange (Li et al. 2020 DDP;
# Rajbhandari et al. 2020 ZeRO): flatten the gradient tree into one flat
# buffer per dtype (the `_eager_fused_allreduce_fn` packing discipline),
# pad to the data-axis size, reduce-scatter so each rank owns a 1/N shard
# ((N-1)/N·B gradient bytes — half the allreduce), update only that shard's
# optimizer state (moments HBM drops by N), then all-gather the update
# shards back ((N-1)/N·B parameter bytes).


def _env_true(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).lower() in ("1", "true", "yes")


def _leaf_dtype(x):
    dt = getattr(x, "dtype", None)
    return jnp.dtype(dt) if dt is not None else jnp.result_type(x)


def _zero_spec(leaves, n: int):
    """Per-dtype flat packing plan: ``{dtype_key: (idxs, sizes, shapes, L,
    Lp)}`` with leaf indices grouped by dtype in first-seen order (the same
    discipline as the eager flat fusion buffer), ``L`` the true packed
    length and ``Lp`` the length padded to a multiple of ``n``."""
    order, groups = [], {}
    for i, leaf in enumerate(leaves):
        k = str(_leaf_dtype(leaf))
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(i)
    spec = {}
    for k in order:
        idxs = groups[k]
        shapes = [tuple(getattr(leaves[i], "shape", ())) for i in idxs]
        sizes = [int(np.prod(s, dtype=np.int64)) for s in shapes]
        L = int(sum(sizes))
        Lp = L + ((-L) % n)
        spec[k] = (idxs, sizes, shapes, L, Lp)
    return spec


def _zero_pack(leaves, entry):
    """Flatten + concatenate one dtype group's leaves, zero-padded to Lp."""
    idxs, _, _, L, Lp = entry
    parts = [jnp.ravel(jnp.asarray(leaves[i])) for i in idxs]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if Lp > L:
        flat = jnp.concatenate([flat, jnp.zeros((Lp - L,), flat.dtype)])
    return flat


def _zero_unpack(flat, entry, out_leaves):
    """Split one dtype group's flat buffer back into `out_leaves` slots."""
    idxs, sizes, shapes, _, _ = entry
    off = 0
    for i, size, shape in zip(idxs, sizes, shapes):
        out_leaves[i] = flat[off:off + size].reshape(shape)
        off += size


def _wire_itemsize(dtype, compression) -> int:
    """Bytes per element the wire actually carries for this dtype under
    `compression` (probed on a host scalar — no device op). Legacy
    fallback only: a blockwise or low-rank compressor changes
    bytes-per-LEAF, not bytes-per-element — use :func:`_wire_bytes_leaf`."""
    try:
        c, _ = compression.compress(np.zeros((), dtype=np.dtype(dtype)))
        return int(np.dtype(c.dtype).itemsize)
    except Exception:
        return int(np.dtype(dtype).itemsize)


def _wire_bytes_leaf(shape, dtype, compression) -> int:
    """Wire bytes one leaf costs per transfer direction: the compressor's
    ``wire_bytes(shape, dtype)`` hook when it has one (truthful for
    blockwise scales and rank-r factors), else the scalar-probe itemsize
    times the element count (correct for elementwise casts only)."""
    shape = tuple(shape)
    hook = getattr(compression, "wire_bytes", None)
    if hook is not None:
        try:
            return int(hook(shape, dtype))
        except Exception as e:
            import logging

            logging.getLogger("horovod_tpu").debug(
                "compressor wire_bytes hook failed (%s); falling back to "
                "the itemsize probe", e)
    size = int(np.prod(shape, dtype=np.int64))
    return size * _wire_itemsize(dtype, compression)


def _record_sync_bytes(mode: str, n: int, wire_bytes: int,
                       gather_bytes: Optional[int] = None) -> None:
    """Trace-time gauge of the per-step gradient-sync wire volume under the
    standard ring model: allreduce moves ``2(N-1)/N·B`` gradient bytes,
    the sharded path ``(N-1)/N·B`` (reduce-scatter) plus an all-gather of
    the parameter updates reported separately — gradient bytes halve, the
    total stays ring-equal, and optimizer HBM drops by N."""
    if not _metrics.enabled():
        return
    ring = (n - 1) / n if n > 1 else 0.0
    factor = 2.0 * ring if mode == "allreduce" else ring
    _metrics.gauge(
        "grad_sync_bytes_per_step",
        help="ring-model gradient bytes exchanged per step",
        mode=mode,
    ).set(factor * wire_bytes)
    if gather_bytes is not None:
        _metrics.gauge(
            "param_gather_bytes_per_step",
            help="ring-model parameter/update bytes all-gathered per step "
                 "(sharded optimizer only)",
            mode=mode,
        ).set(ring * gather_bytes)


def _tree_sync_wire_bytes(grads, compression, *, axis=None) -> int:
    """Per-step wire bytes of one gradient exchange direction, priced
    per leaf through the compressor's ``wire_bytes`` hook. With ``axis``
    given, eager stacked ``[N, ...]`` leaves bill their per-rank shape —
    every rank sends ONE contribution, not N."""
    total = 0
    for g in jax.tree_util.tree_leaves(grads):
        shape = tuple(getattr(g, "shape", ()))
        if axis is not None and shape and _C._is_stacked(g, axis):
            shape = shape[1:]
        total += _wire_bytes_leaf(shape, _leaf_dtype(g), compression)
    return total


def _zero_pack_rows(leaves, entry, stacked_flags, n):
    """[N, Lp] matrix of per-rank flat contributions for one dtype group:
    stacked leaves supply their own rows, replicated leaves tile."""
    idxs, sizes, _, L, Lp = entry
    rows = []
    for i, size in zip(idxs, sizes):
        l = jnp.asarray(leaves[i])
        if stacked_flags[i]:
            rows.append(l.reshape(n, size))
        else:
            rows.append(jnp.broadcast_to(l.reshape(1, size), (n, size)))
    m = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=1)
    if Lp > L:
        m = jnp.concatenate([m, jnp.zeros((n, Lp - L), m.dtype)], axis=1)
    return m


def _zero_init(optimizer, params, n: int, *, error_feedback: bool,
               compression=None, bucket_bytes: Optional[int] = None):
    """Build the sharded optimizer state: per-dtype flat param buffers are
    padded and reshaped ``[N, shard]``, and the inner optimizer is
    ``jax.vmap``-initialized over the rank axis so EVERY state leaf —
    moments, counts, injected hyperparams — carries a leading rank dim.
    That uniform leading axis is what lets ``shard_map`` step builders spec
    the whole state ``P(data)`` (each rank holds only its own row).
    Factorized (PowerSGD) compression adds the warm-start Q tree, tiled
    ``[N, m, r]`` to keep the leading-axis contract.

    ``bucket_bytes`` (the overlap path) splits the per-dtype buffers into
    the reverse-emission bucket groups, one ``[N, shard_k]`` state buffer
    per bucket (error-feedback residuals keyed by bucket) — the exact
    layout :func:`_zero_update` exchanges per bucket."""
    leaves = jax.tree_util.tree_leaves(params)
    groups = _zero_groups(leaves, n, bucket_bytes)
    shards = {
        k: _ov.pack_group(leaves, g).reshape(n, -1)
        for k, g in groups.items()
    }
    inner = jax.vmap(optimizer.init)(shards)
    if compression is not None and getattr(compression, "factorized", False):
        residual = {
            k: jnp.zeros((n, g.Lp), dtype=jnp.dtype(g.dtype))
            for k, g in groups.items()
        }
        return _PowerSGDState(
            inner, residual, _powersgd_q_init(params, compression, n))
    if error_feedback:
        residual = {
            k: jnp.zeros((n, g.Lp), dtype=jnp.dtype(g.dtype))
            for k, g in groups.items()
        }
        return _EFState(inner, residual)
    return inner


def _maybe_place_sharded(state, ax):
    """Eagerly place a freshly built sharded state with its leading rank dim
    over the data axis, so the ZeRO-1 HBM saving is real from step 0 (and
    donation keeps the layout steady). No-op on tracers / before init."""
    if not basics.is_initialized():
        return state
    try:
        sh = NamedSharding(basics.mesh(), P(ax))
    except Exception:
        return state

    def place(x):
        if _C._is_tracer(x) or not getattr(x, "shape", ()):
            return x
        try:
            return jax.device_put(x, sh)
        except Exception:
            return x

    return jax.tree_util.tree_map(place, state)


def _zero_groups(shape_leaves, n: int, bucket_bytes: Optional[int]):
    """Exchange groups for the sharded update, all in the segment form of
    :mod:`horovod_tpu.ops.overlap`: without ``bucket_bytes`` one
    whole-leaf group per dtype (the monolithic flat packing, keys =
    dtype strings — the historical state layout); with it the
    reverse-emission :class:`~horovod_tpu.ops.overlap.BucketPlan`
    partition (~``bucket_bytes`` per group, leaf splitting allowed, keys
    ``dtype#k``) — one collective per bucket, the overlappable
    schedule."""
    if bucket_bytes:
        return _ov.plan_for(shape_leaves, n, bucket_bytes).groups
    groups = {}
    for k, (idxs, sizes, _shapes, L, Lp) in _zero_spec(
            shape_leaves, n).items():
        segs = tuple(
            _ov.Segment(i, 0, sz) for i, sz in zip(idxs, sizes)
        )
        groups[k] = _ov.Bucket(key=k, dtype=k, segs=segs, L=L, Lp=Lp)
    return groups


def _zero_update(grads, state, params, *, optimizer, compression,
                 error_feedback, op, predivide, ax, roundtrip, extra,
                 bucket_bytes: Optional[int] = None):
    """One sharded (ZeRO-1) update. Three dispatch modes, same math:

    - **bound axis** (inside ``shard_map``): the per-rank hot path —
      flat-pack, ``lax.psum_scatter`` the (compressed) buffer, update this
      rank's shard, ``lax.all_gather`` the update shards back.
    - **traced, unbound** (global jit / pjit): replicated semantics — XLA's
      sharding propagation plus the state's ``[N, shard]`` layout perform
      the reduce-scatter/all-gather placement; the rank axis is vmapped.
    - **eager**: dispatches the real eager ``reducescatter`` collective on
      the packed buffer (stacked ``[N, Lp]`` when error feedback makes the
      per-rank contributions differ), then vmaps the shard updates.

    Quantized (int8) compression swaps the reduce-scatter for the
    overflow-safe int8 ring (:func:`collective.quantized_psum_scatter`:
    int8 + bf16 scales on the wire, f32 accumulation per shard) on the
    f32/f64 dtype groups; integer and 16-bit groups ride uncompressed.
    Factorized (PowerSGD) compression dispatches to
    :func:`_zero_update_powersgd`.

    ``bucket_bytes`` (``DistributedOptimizer(overlap=True)``) swaps the
    per-dtype exchange for one reduce-scatter per reverse-emission
    bucket — each depending only on its own leaves' cotangents, so the
    collectives can launch while the remaining backward still runs —
    with error-feedback residuals keyed by bucket and the update shards
    still returned through a SINGLE trailing all-gather per dtype (the
    gather leg has nothing to overlap with and fuses best whole).
    """
    if getattr(compression, "factorized", False):
        return _zero_update_powersgd(
            grads, state, params, optimizer=optimizer,
            compression=compression, op=op, ax=ax, extra=extra)
    n = _C._axis_size(ax)
    quantized = getattr(compression, "quantized", False)
    qblock = int(getattr(compression, "block", 0) or 0)

    def _wire_rt(x):
        """Per-rank wire contribution of a quantized flat buffer — the
        chunk-aligned int8 roundtrip matching the reduce-scatter layout
        exactly, so EF residuals equal what the ring actually dropped."""
        one = lambda v: quantize_roundtrip_chunked(v, n, qblock)  # noqa: E731
        return one(x) if x.ndim == 1 else jax.vmap(one)(x)

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    p_leaves = jax.tree_util.tree_leaves(params) if params is not None else None
    inner = state.inner if error_feedback else state
    residual = state.residual if error_feedback else None
    traced = any(_C._is_tracer(l) for l in leaves)
    bound = traced and _C._axis_bound(ax)
    # eager per-rank (stacked [N, ...]) gradient leaves contribute their
    # per-rank shape to the packing plan — the update tree is param-shaped
    stacked_flags = [
        (not traced) and _C._is_stacked(l, ax) for l in leaves
    ]

    shape_leaves = [
        jax.ShapeDtypeStruct(tuple(l.shape[1:]), jnp.dtype(l.dtype)) if st
        else jax.ShapeDtypeStruct(
            tuple(getattr(l, "shape", ())), _leaf_dtype(l))
        for l, st in zip(leaves, stacked_flags)
    ]
    groups = _zero_groups(shape_leaves, n, bucket_bytes)

    def _pack_rows(g):
        """[N, Lp] matrix of per-rank flat contributions (eager path)."""
        return _ov.pack_group_rows(leaves, g, stacked_flags, n)

    gshards = {}
    pshards = {} if p_leaves is not None else None
    new_residual = {}
    wire_bytes = 0
    gather_bytes = 0
    idx = _C._flat_axis_index(basics.mesh(), ax) if bound else None

    for key, g in groups.items():
        Lp = g.Lp
        s = Lp // n
        # the quantized ring needs a single named axis for its all_to_all;
        # an axis pair falls back to shipping the roundtripped values
        # through the plain reduce-scatter (same math, modeled wire). A
        # flat buffer below the min-quantize floor rides uncompressed —
        # the per-chunk block padding would cost more than fp32.
        qgroup = (
            quantized and _quantizable(jnp.dtype(g.dtype))
            and Lp >= int(getattr(compression, "min_quant_elems", 0))
        )
        qkernel = qgroup and not isinstance(ax, tuple)
        flat = (
            None
            if any(stacked_flags[i] for i in g.idxs)
            else _ov.pack_group(leaves, g)  # [Lp]
        )
        if bound:
            pre = None
            if error_feedback:
                corrected = flat + residual[key][0]
                if qgroup and qkernel and _pallas_on() and not (
                        op == Average and predivide != 1.0):
                    # fused Pallas path: ONE quantize pass serves both the
                    # EF residual and the all_to_all payload (the wire
                    # image is of `corrected` itself, so reuse is exact;
                    # a predivide would rescale the wire and break it)
                    q_w, sc_w, rt = quantize_chunked(corrected, n, qblock)
                    pre = (q_w, sc_w)
                elif qgroup:
                    rt = _wire_rt(corrected)
                else:
                    rt = roundtrip(corrected)
                new_residual[key] = (corrected - rt)[None]
                send = corrected
            else:
                send = flat
            if op == Average and predivide != 1.0:
                send = send / predivide
            if qkernel:
                shard = _C.quantized_psum_scatter(
                    send, ax, block=qblock, pre=pre)
                ctx = None
            else:
                comp, ctx = (
                    (_wire_rt(send), None) if qgroup
                    else compression.compress(send)
                )
                shard = lax.psum_scatter(
                    comp, ax, scatter_dimension=0, tiled=True)
            if op == Average and predivide == 1.0:
                shard = _C._div(shard, n)
            if not qgroup:
                shard = compression.decompress(shard, ctx)
            if op == Average and predivide != 1.0:
                shard = shard * (predivide / n)
            gshards[key] = shard[None]
            if p_leaves is not None:
                pflat = _ov.pack_group(p_leaves, g)
                pshards[key] = lax.dynamic_slice(pflat, (idx * s,), (s,))[None]
        elif traced:
            # unbound global-jit: replicated semantics (XLA already placed
            # the cross-chip reduction); model the wire roundtrip exactly
            # as allreduce() does for global values
            if error_feedback:
                corrected = flat[None] + residual[key]       # [N, Lp]
                contrib = (
                    _wire_rt(corrected) if qgroup else roundtrip(corrected)
                )
                new_residual[key] = corrected - contrib
                reduced = (
                    contrib.mean(axis=0) if op == Average
                    else contrib.sum(axis=0)
                )
            else:
                r = _wire_rt(flat) if qgroup else roundtrip(flat)
                reduced = r if op == Average else r * n
            gshards[key] = reduced.reshape(n, s)
            if p_leaves is not None:
                pshards[key] = _ov.pack_group(p_leaves, g).reshape(n, s)
        else:
            # eager: the real reduce-scatter collective on the packed buffer
            per_rank = error_feedback or any(
                stacked_flags[i] for i in g.idxs
            )
            if error_feedback:
                corrected = _pack_rows(g) + residual[key]       # [N, Lp]
                rt = _wire_rt(corrected) if qgroup else roundtrip(corrected)
                new_residual[key] = corrected - rt
                send = corrected
            else:
                send = _pack_rows(g) if per_rank else flat
            if op == Average and predivide != 1.0:
                send = send / predivide
            if qkernel:
                if per_rank:
                    send = jax.device_put(
                        send, NamedSharding(basics.mesh(), P(ax)))
                shard = _C.quantized_reducescatter(
                    send, axis=ax, block=qblock)                # [N, s]
                ctx = None
            else:
                comp, ctx = (
                    (_wire_rt(send), None) if qgroup
                    else compression.compress(send)
                )
                if per_rank:
                    # per-rank rows: dispatch stacked over the data axis
                    comp = jax.device_put(
                        comp, NamedSharding(basics.mesh(), P(ax)))
                shard = _C.reducescatter(comp, Sum, axis=ax)    # [N, s]
            if op == Average and predivide == 1.0:
                shard = _C._div(shard, n)
            if not qgroup:
                shard = compression.decompress(shard, ctx)
            if op == Average and predivide != 1.0:
                shard = shard * (predivide / n)
            gshards[key] = shard
            if p_leaves is not None:
                pshards[key] = _ov.pack_group(p_leaves, g).reshape(n, s)
        wire_bytes += _wire_bytes_leaf(
            (Lp,), jnp.dtype(g.dtype), compression)
        gather_bytes += Lp * jnp.dtype(g.dtype).itemsize

    if error_feedback:
        for key, g in groups.items():
            new_residual[key] = new_residual[key].astype(jnp.dtype(g.dtype))

    # fence the vmapped optimizer into a self-contained fusion island:
    # with identical inputs its HLO (and therefore XLA's rounding — fma
    # vs separate mul/add) is the same in every program that embeds it,
    # which is what lets the ZeRO-3 step (optim._fsdp_update, fencing the
    # same subgraph the same way) pin its trajectory bit-identical to
    # this one
    if p_leaves is not None:
        def upd(g, st, p):
            return optimizer.update(g, st, p, **extra)

        gshards, inner, pshards = lax.optimization_barrier(
            (gshards, inner, pshards))
        upd_shards, new_inner = jax.vmap(upd)(gshards, inner, pshards)
    else:
        def upd(g, st):
            return optimizer.update(g, st, **extra)

        gshards, inner = lax.optimization_barrier((gshards, inner))
        upd_shards, new_inner = jax.vmap(upd)(gshards, inner)
    upd_shards, new_inner = lax.optimization_barrier(
        (upd_shards, new_inner))

    # gather leg: ONE trailing all-gather per dtype — the bucketed path
    # concatenates this rank's per-bucket update shards first (the gather
    # has nothing left to overlap with, and one fused transfer beats K),
    # then re-slices the gathered [N, sum(s_k)] blocks back per bucket
    full_flats = {}
    if bound:
        by_dtype: dict = {}
        for key, g in groups.items():
            by_dtype.setdefault(g.dtype, []).append(key)
        for keys in by_dtype.values():
            cats = [upd_shards[k][0] for k in keys]
            cat = cats[0] if len(cats) == 1 else jnp.concatenate(cats)
            S = cat.shape[0]
            gat = lax.all_gather(cat, ax, axis=0, tiled=True).reshape(n, S)
            off = 0
            for k in keys:
                s_k = groups[k].Lp // n
                full_flats[k] = (
                    gat[:, off:off + s_k].reshape(-1)[:groups[k].L]
                )
                off += s_k
    else:
        for key, g in groups.items():
            full_flats[key] = upd_shards[key].reshape(-1)[:g.L]
    out_leaves = _ov.assemble(
        full_flats, groups,
        [s.shape for s in shape_leaves],
        [s.dtype for s in shape_leaves],
    )
    updates = jax.tree_util.tree_unflatten(treedef, out_leaves)

    _record_sync_bytes("sharded", n, wire_bytes, gather_bytes)
    _ov._record_buckets("sharded", len(groups))
    new_state = (
        _EFState(new_inner, new_residual) if error_feedback else new_inner
    )
    return updates, new_state


def _zero_update_powersgd(grads, state, params, *, optimizer, compression,
                          op, ax, extra):
    """ZeRO-1 update under PowerSGD: every >=2-D float leaf syncs only its
    rank-r P/Q factors (allreduce of two small matrices), 1-D float leaves
    ride the int8 wire, integer/16-bit leaves ride uncompressed — after
    which the MEAN gradient is known replicated, so each rank slices its
    own flat shard with no further collective, vmaps the shard update, and
    all-gathers the update shards exactly like :func:`_zero_update`.

    Error feedback stays in the per-dtype flat ``[N, Lp]`` residual
    packing (``residual_i = corrected_i - approx_mean`` for factorized
    leaves; the int8 wire roundtrip for fallback leaves), so the
    mass-preserving reshard path is unchanged.
    """
    n = _C._axis_size(ax)
    fallback = getattr(compression, "fallback", Int8Compressor)
    block = int(getattr(compression, "block", 0) or 0)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    p_leaves = jax.tree_util.tree_leaves(params) if params is not None else None
    inner, residual, q_tree = state.inner, state.residual, state.q
    q_leaves = _q_leaves(q_tree)
    traced = any(_C._is_tracer(l) for l in leaves)
    bound = traced and _C._axis_bound(ax)
    stacked_flags = [
        (not traced) and _C._is_stacked(l, ax) for l in leaves
    ]

    shapes = [
        tuple(l.shape[1:]) if st else tuple(getattr(l, "shape", ()))
        for l, st in zip(leaves, stacked_flags)
    ]
    spec = _zero_spec(
        [jax.ShapeDtypeStruct(s, _leaf_dtype(l))
         for s, l in zip(shapes, leaves)], n)

    # 1. per-rank corrected leaves: bound mode unpacks this rank's
    # corrected flat buffer; the others carry a leading rank axis [N, ...]
    corrected = [None] * len(leaves)
    for key, entry in spec.items():
        if bound:
            cflat = _zero_pack(leaves, entry) + residual[key][0]
            _zero_unpack(cflat, entry, corrected)
        else:
            rows = (
                _zero_pack_rows(leaves, entry, stacked_flags, n)
                + residual[key]
            )  # [N, Lp]
            off = 0
            for i, size, shape in zip(entry[0], entry[1], entry[2]):
                corrected[i] = rows[:, off:off + size].reshape((n,) + shape)
                off += size

    def _reduce_mean_bound(x):
        from horovod_tpu.ops.collective import allreduce

        return allreduce(x, Average, axis=ax)

    # 2. per-leaf sync: factorized / int8 fallback / uncompressed
    reduced = [None] * len(leaves)
    res_leaves = [None] * len(leaves)
    new_q = [None] * len(leaves)
    wire_bytes = 0
    for i, (c, shape) in enumerate(zip(corrected, shapes)):
        dt = _leaf_dtype(leaves[i])
        wire_bytes += _wire_bytes_leaf(shape, dt, compression)
        if q_leaves[i] is not None:
            qmat = q_leaves[i][0]  # strip the (identical-rows) rank axis
            if bound:
                m2d = c.reshape(shape[0], -1)
                approx, qn = _psgd_factor_sync(m2d, qmat, _reduce_mean_bound)
                res_leaves[i] = (m2d - approx).reshape(shape)
                red = approx.reshape(shape)
                new_q[i] = qn[None]
            else:
                m2d = c.mean(axis=0).reshape(shape[0], -1)
                approx, qn = _psgd_factor_sync(m2d, qmat, lambda x: x)
                red = approx.reshape(shape)
                res_leaves[i] = c - red[None]
                new_q[i] = jnp.broadcast_to(qn[None], (n,) + qn.shape)
            reduced[i] = red * n if op == Sum else red
        elif _quantizable(dt):
            if bound:
                rt = int8_roundtrip(c, block)
                res_leaves[i] = c - rt
                from horovod_tpu.ops.collective import allreduce

                reduced[i] = allreduce(c, op, axis=ax, compression=fallback)
            else:
                rt = jax.vmap(lambda v: int8_roundtrip(v, block))(c)
                res_leaves[i] = c - rt
                red = rt.mean(axis=0)
                reduced[i] = red * n if op == Sum else red
        else:
            res_leaves[i] = jnp.zeros_like(c)
            if bound:
                from horovod_tpu.ops.collective import allreduce

                reduced[i] = allreduce(c, op, axis=ax)
            else:
                red = c.sum(axis=0) if op == Sum else _C._div(c.sum(axis=0), n)
                reduced[i] = red.astype(dt)

    # 3. repack: the reduced tree is fully known (replicated), so shards
    # are slices — no further gradient collective
    gshards = {}
    pshards = {} if p_leaves is not None else None
    new_residual = {}
    gather_bytes = 0
    idx = _C._flat_axis_index(basics.mesh(), ax) if bound else None
    all_stacked = [True] * len(leaves)
    for key, entry in spec.items():
        Lp = entry[4]
        s = Lp // n
        red_flat = _zero_pack(reduced, entry)                   # [Lp]
        if bound:
            gshards[key] = lax.dynamic_slice(red_flat, (idx * s,), (s,))[None]
            new_residual[key] = _zero_pack(res_leaves, entry)[None]
            if p_leaves is not None:
                pflat = _zero_pack(p_leaves, entry)
                pshards[key] = lax.dynamic_slice(pflat, (idx * s,), (s,))[None]
        else:
            gshards[key] = red_flat.reshape(n, s)
            new_residual[key] = _zero_pack_rows(
                res_leaves, entry, all_stacked, n)              # [N, Lp]
            if p_leaves is not None:
                pshards[key] = _zero_pack(p_leaves, entry).reshape(n, s)
        new_residual[key] = new_residual[key].astype(jnp.dtype(key))
        gather_bytes += Lp * jnp.dtype(key).itemsize

    if p_leaves is not None:
        def upd(g, st, p):
            return optimizer.update(g, st, p, **extra)

        upd_shards, new_inner = jax.vmap(upd)(gshards, inner, pshards)
    else:
        def upd(g, st):
            return optimizer.update(g, st, **extra)

        upd_shards, new_inner = jax.vmap(upd)(gshards, inner)

    out_leaves = [None] * len(leaves)
    for key, entry in spec.items():
        L = entry[3]
        if bound:
            full = lax.all_gather(upd_shards[key][0], ax, axis=0, tiled=True)
        else:
            full = upd_shards[key].reshape(-1)
        _zero_unpack(full[:L], entry, out_leaves)
    updates = jax.tree_util.tree_unflatten(treedef, out_leaves)

    # P/Q (and the int8-fallback leaves) ride full ring ALLREDUCES, i.e.
    # 2(N-1)/N per wire byte where _record_sync_bytes' sharded mode prices
    # (N-1)/N — double the wire sum so the gauge stays truthful
    _record_sync_bytes("sharded", n, 2 * wire_bytes, gather_bytes)
    new_state = _PowerSGDState(
        new_inner, new_residual,
        jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(q_tree, is_leaf=_q_is_leaf), new_q),
    )
    return updates, new_state


# --------------------------------------------------------------------------
# ZeRO-3 (FSDP): parameter shards + gather-on-use
#
# ZeRO-1 (above) shards gradients and optimizer state but keeps a full
# parameter replica on every chip. ZeRO-3 shards the parameters themselves
# in the SAME per-bucket flat [N, shard] packing (the segment-group
# machinery of ops/overlap.py): the step re-materializes the full tree with
# one all-gather per bucket just before the forward consumes it, discards
# it (``jax.checkpoint`` re-gathers in the backward), and the gradient
# arrives back as shards for free — the autodiff transpose of a tiled
# ``all_gather`` IS the tiled ``psum_scatter``, so differentiating through
# the gather performs the per-bucket gradient reduce-scatter ZeRO-1 issues
# explicitly, bit for bit. No code path duplicates the exchange: ZeRO-3 is
# a pack/gather stage over the ZeRO-1 group plan, and the vmapped shard
# update below is ZeRO-1's own.

FSDP_WIRE_ENV = "HOROVOD_FSDP_WIRE"


def _fsdp_wire() -> str:
    """Resolve the parameter-gather wire format (``HOROVOD_FSDP_WIRE``):
    ``none`` (full-precision gather) or ``int8`` (blockwise int8 + bf16
    scales — :func:`collective.quantized_all_gather`). Read at trace
    time; the SAME resolution prices the ``param_gather_bytes_per_step``
    gauge, so the model and the wire can never disagree."""
    wire = os.environ.get(FSDP_WIRE_ENV, "none").lower()
    if wire not in ("none", "int8"):
        raise ValueError(
            f"{FSDP_WIRE_ENV} must be 'none' or 'int8', got {wire!r}")
    return wire


class _FsdpMeta(NamedTuple):
    """Static (hashable) half of :class:`FsdpParams`: everything needed to
    re-derive the group plan and re-assemble the original tree."""
    treedef: Any
    shapes: tuple
    dtypes: tuple
    axis: Any
    bucket_bytes: Optional[int]


class FsdpParams:
    """ZeRO-3 parameter shards: ``{group_key: [N, shard]}`` flat buffers in
    the ZeRO-1 packing (per-dtype groups, or ``dtype#k`` bucket groups
    under ``bucket_bytes``) plus the static metadata to re-assemble the
    tree. Registered as a pytree node, so ``jax.grad`` w.r.t. one returns
    gradient shards of the same type, ``optax.apply_updates`` applies
    update shards shard-wise, and ``shard_map`` specs the whole thing
    ``P(axis)`` as a pytree prefix. Build with :func:`fsdp_pack_params`;
    re-materialize with :func:`fsdp_gather_params` (in-step, collective)
    or :func:`fsdp_unpack_params` (host-side)."""

    __slots__ = ("shards", "meta")

    def __init__(self, shards: dict, meta: _FsdpMeta):
        self.shards = dict(shards)
        self.meta = meta

    @property
    def num_shards(self) -> int:
        return next(iter(self.shards.values())).shape[0]

    def __repr__(self):
        return (f"FsdpParams(groups={sorted(self.shards)}, "
                f"axis={self.meta.axis!r})")


def _fsdp_flatten(fp):
    keys = tuple(sorted(fp.shards))
    return [fp.shards[k] for k in keys], (keys, fp.meta)


def _fsdp_unflatten(aux, children):
    keys, meta = aux
    return FsdpParams(dict(zip(keys, children)), meta)


jax.tree_util.register_pytree_node(FsdpParams, _fsdp_flatten, _fsdp_unflatten)


def _fsdp_groups(meta: _FsdpMeta, n: int):
    """Re-derive the exchange-group plan from the pack metadata. Group
    boundaries depend only on the leaf shapes and ``bucket_bytes`` — never
    on the world size (only the ``Lp`` padding does) — which is what makes
    :func:`fsdp_reshard_params` a pure re-pad."""
    shape_leaves = [
        jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
        for s, d in zip(meta.shapes, meta.dtypes)
    ]
    return _zero_groups(shape_leaves, n, meta.bucket_bytes)


def fsdp_pack_params(params, *, axis=None, bucket_bytes: Optional[int] = None):
    """Pack a parameter tree into ZeRO-3 shards (:class:`FsdpParams`).

    The flat packing is byte-identical to :func:`_zero_init`'s state
    layout (same ``_zero_groups`` plan), so
    ``DistributedOptimizer(shard_params=True).init(fp)`` produces
    optimizer state bit-identical to the ZeRO-1 state for the same tree —
    and :func:`reshard_optimizer_state` re-packs both with one plan.
    ``bucket_bytes`` sets the gather granularity (the overlap unit of the
    gather-on-use schedule); default is one group per dtype. The shard
    rows are eagerly placed ``P(axis)`` so the HBM saving is real from
    step 0."""
    ax = _C._axis(axis)
    n = _C._axis_size(ax)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    meta = _FsdpMeta(
        treedef=treedef,
        shapes=tuple(tuple(getattr(l, "shape", ())) for l in leaves),
        dtypes=tuple(str(_leaf_dtype(l)) for l in leaves),
        axis=ax,
        bucket_bytes=bucket_bytes,
    )
    groups = _fsdp_groups(meta, n)
    shards = {
        k: _ov.pack_group(leaves, g).reshape(n, -1)
        for k, g in groups.items()
    }
    return _maybe_place_sharded(FsdpParams(shards, meta), ax)


def fsdp_unpack_params(fp: FsdpParams):
    """Re-assemble the full parameter tree from ZeRO-3 shards, host-side
    (checkpoint consolidation, eval, publishing). Inside a traced step use
    :func:`fsdp_gather_params` — the collective gather-on-use leg."""
    n = fp.num_shards
    groups = _fsdp_groups(fp.meta, n)
    flats = {
        k: jnp.asarray(fp.shards[k]).reshape(-1)[:g.L]
        for k, g in groups.items()
    }
    leaves = _ov.assemble(
        flats, groups, [tuple(s) for s in fp.meta.shapes],
        [jnp.dtype(d) for d in fp.meta.dtypes],
    )
    return jax.tree_util.tree_unflatten(fp.meta.treedef, leaves)


def fsdp_gather_params(fp: FsdpParams, *, wire: Optional[str] = None):
    """The gather-on-use leg: re-materialize the full parameter tree from
    shards with ONE all-gather per group, issue-order pinned.

    Inside ``shard_map`` (bound axis) each group's ``[s]`` shard rides a
    tiled ``lax.all_gather`` — routed through the hierarchical ICI/DCN
    composition for a ``(cross, local)`` axis pair, or the int8 wire
    (``HOROVOD_FSDP_WIRE=int8`` /
    :func:`collective.quantized_all_gather`) for quantizable groups —
    then unpadded and re-assembled. Consecutive gathers are barrier-
    chained (``HOROVOD_OVERLAP_BARRIER``, default on) so every schedule
    issues them in pack order: the forward consumes bucket k while bucket
    k+1's gather is still in flight. Under ``jax.checkpoint`` the
    backward re-gathers instead of holding the gathered tree — the ZeRO-3
    memory deal — and the gather's transpose reduce-scatters the gradient
    shards back with no extra code.

    Unbound (global jit / eager) the shards are replicated ``[N, s]``
    rows: re-assembly is a reshape, with the int8 wire modeled as a
    per-row roundtrip so traced-unbound values match the bound wire."""
    from horovod_tpu.compression import (
        INT8_BLOCK, MIN_QUANT_ELEMS, dequantize_blockwise,
        quantize_blockwise,
    )

    meta = fp.meta
    ax = meta.axis
    vals = list(fp.shards.values())
    traced = any(_C._is_tracer(v) for v in vals)
    bound = traced and _C._axis_bound(ax)
    n = _C._axis_size(ax) if bound else fp.num_shards
    groups = _fsdp_groups(meta, n)
    if wire is None:
        wire = _fsdp_wire()

    def _roundtrip_row(row):
        q, sc = quantize_blockwise(row, INT8_BLOCK)
        return dequantize_blockwise(
            q, sc, row.dtype, INT8_BLOCK)[:row.shape[0]]

    keys, fulls = [], []
    for key, g in groups.items():
        qgroup = (
            wire == "int8" and _quantizable(jnp.dtype(g.dtype))
            and g.Lp >= MIN_QUANT_ELEMS
        )
        if bound:
            local = fp.shards[key][0]                          # [s]
            if qgroup and not isinstance(ax, tuple):
                full = _C.quantized_all_gather(local, ax, block=INT8_BLOCK)
            else:
                if qgroup:
                    # axis pair (hierarchical): the quantized kernel needs
                    # a single named axis — ship the roundtripped values
                    # through the routed gather (same math, modeled wire)
                    local = _roundtrip_row(local)
                full = _C.allgather(local, axis=ax)            # [n*s]
        else:
            rows = jnp.asarray(fp.shards[key])                 # [N, s]
            if qgroup:
                rows = jax.vmap(_roundtrip_row)(rows)
            full = rows.reshape(-1)
        keys.append(key)
        fulls.append(full)
    if bound and len(fulls) > 1 and _ov.barrier_enabled():
        fulls = _ov.chain_barriers(fulls)
    flats = {k: f[:groups[k].L] for k, f in zip(keys, fulls)}
    leaves = _ov.assemble(
        flats, groups, [tuple(s) for s in meta.shapes],
        [jnp.dtype(d) for d in meta.dtypes],
    )
    return jax.tree_util.tree_unflatten(meta.treedef, leaves)


def _fsdp_gather_wire_bytes(groups, n: int, wire: str) -> int:
    """Wire image of ONE parameter all-gather: fp32 groups move their full
    padded length; int8 groups move each rank's block-padded shard as int8
    plus one bf16 scale per block, times N ranks. The analytic twin is
    :func:`tools.scaling_projection.fsdp_gather_wire_bytes` — a test pins
    them equal."""
    from horovod_tpu.compression import (
        INT8_BLOCK, MIN_QUANT_ELEMS, _SCALE_BYTES,
    )

    total = 0
    for g in groups.values():
        dt = jnp.dtype(g.dtype)
        if (wire == "int8" and _quantizable(dt)
                and g.Lp >= MIN_QUANT_ELEMS):
            s = g.Lp // n
            sp = s + ((-s) % INT8_BLOCK)
            total += n * (sp + (sp // INT8_BLOCK) * _SCALE_BYTES)
        else:
            total += g.Lp * dt.itemsize
    return total


def _fsdp_update(grads, state, params, *, optimizer, op, ax, extra):
    """One ZeRO-3 update. The gradient already arrived REDUCED: inside
    ``shard_map`` the gather's transpose emitted
    ``psum_scatter(pack(local_grads))`` — the SUM over ranks of each
    rank's packed gradient shard, exactly the buffer ZeRO-1's explicit
    reduce-scatter produces — so this function only divides for Average,
    vmaps the inner update over the rank axis, and returns the update
    shards AS SHARDS (no trailing all-gather: the parameters stay
    sharded; the next step's gather-on-use sees ``shards + updates``,
    and gather distributes over the elementwise add, which is the whole
    bit-identity argument vs ZeRO-1)."""
    if not isinstance(grads, FsdpParams):
        raise TypeError(
            "DistributedOptimizer(shard_params=True) updates FsdpParams "
            "gradient shards — differentiate the loss w.r.t. the packed "
            "params from fsdp_pack_params (the gather's transpose returns "
            f"shards), got {type(grads).__name__}"
        )
    meta = grads.meta
    vals = list(grads.shards.values())
    traced = any(_C._is_tracer(v) for v in vals)
    bound = traced and _C._axis_bound(ax)
    n = _C._axis_size(ax) if bound else grads.num_shards
    groups = _fsdp_groups(meta, n)

    gshards = dict(grads.shards)
    if bound:
        if op == Average:
            gshards = {k: _C._div(v, n) for k, v in gshards.items()}
    elif op == Sum:
        # unbound/eager replicated semantics: every rank would contribute
        # the same global gradient (mirrors _zero_update's unbound mode)
        gshards = {k: v * n for k, v in gshards.items()}

    grad_wire = sum(
        g.Lp * jnp.dtype(g.dtype).itemsize for g in groups.values()
    )
    # the gather traces a data-dependent number of times under
    # jax.checkpoint (forward + backward re-gather), so the gauges are
    # recorded HERE, once per step: the gather leg bills 2x — its wire
    # runs twice per step by construction
    gather_wire = _fsdp_gather_wire_bytes(groups, n, _fsdp_wire())
    _record_sync_bytes("zero3", n, grad_wire, 2 * gather_wire)
    _ov._record_buckets("zero3", len(groups))

    # the same fusion fence as _zero_update around the same vmapped
    # subgraph: identical inputs → identical self-contained HLO →
    # identical XLA rounding (fma/rsqrt choices), the compiled half of
    # the ZeRO-3-vs-ZeRO-1 bit-identity argument
    pshards = params.shards if isinstance(params, FsdpParams) else None
    if pshards is not None:
        def upd(g, st, p):
            return optimizer.update(g, st, p, **extra)

        gshards, state, pshards = lax.optimization_barrier(
            (gshards, state, pshards))
        upd_shards, new_inner = jax.vmap(upd)(gshards, state, pshards)
    else:
        def upd(g, st):
            return optimizer.update(g, st, **extra)

        gshards, state = lax.optimization_barrier((gshards, state))
        upd_shards, new_inner = jax.vmap(upd)(gshards, state)
    upd_shards, new_inner = lax.optimization_barrier(
        (upd_shards, new_inner))
    if bound:
        # Materialization fence for the caller's `p + u` apply add. The
        # XLA CPU backend contracts the inner optimizer's trailing
        # `-lr * x` multiply into the consumer's add (a single-rounding
        # fma) even across optimization_barrier, which would put the new
        # params 1 ulp off ZeRO-1 — whose updates cross a real
        # all_gather and therefore materialize before the add. An
        # identity ppermute (every rank sends to itself: zero
        # cross-device bytes, so it is not billed to the sync gauges)
        # forces the update shards to materialize the same way,
        # completing the bitwise-equality argument.
        perm = [(i, i) for i in range(n)]
        upd_shards = {
            k: lax.ppermute(v, ax, perm) for k, v in upd_shards.items()
        }
    return FsdpParams(upd_shards, meta), new_inner


def fsdp_reshard_params(fp: FsdpParams, *, to_size: Optional[int] = None):
    """Re-pack ZeRO-3 parameter shards for a different world size (the
    parameter half of the elastic/checkpoint consolidation;
    :func:`reshard_optimizer_state` handles the state half and accepts
    the SAME :class:`FsdpParams` as its ``params`` argument). Group
    boundaries are world-size independent, so this is unpad-to-``L`` →
    re-pad for ``to_size`` → reshape ``[N', shard']`` per group — no
    collective, no device math."""
    n_new = int(to_size) if to_size is not None else basics.size()
    n_old = fp.num_shards
    if n_old == n_new:
        return fp
    old_groups = _fsdp_groups(fp.meta, n_old)
    new_groups = _fsdp_groups(fp.meta, n_new)
    shards = {}
    for k, g_new in new_groups.items():
        g_old = old_groups[k]
        flat = jnp.asarray(fp.shards[k]).reshape(-1)[:g_old.L]
        if g_new.Lp > g_new.L:
            flat = jnp.concatenate(
                [flat, jnp.zeros((g_new.Lp - g_new.L,), flat.dtype)])
        shards[k] = flat.reshape(n_new, -1)
    return _maybe_place_sharded(FsdpParams(shards, fp.meta), fp.meta.axis)


def reshard_optimizer_state(state, params, *, to_size: Optional[int] = None,
                            axis=None, bucket_bytes: Optional[int] = None):
    """Re-pack a sharded (ZeRO-1) optimizer state for a different data-axis
    size — the restore-side consolidation step after a world-size change.

    Two callers: checkpoint restore onto a differently-sized job
    (:func:`horovod_tpu.checkpoint.consolidate_opt_state`), and the elastic
    coordinator's *live* generation change
    (:mod:`horovod_tpu.resilience.elastic`), which calls this between mesh
    re-formation and the rebuilt step function's first replayed step.

    ``checkpoint.save`` persists the *consolidated* ``[N_old, shard]``
    arrays (rank 0 holds the addressable global view); on restore to
    ``to_size`` ranks (default: the current :func:`horovod_tpu.size`), each
    2-D leaf is unpadded back to its true flat length (derived from
    ``params`` — the same tree the state was initialized from), re-padded
    for the new size, and reshaped ``[N_new, shard']``. Per-rank vmapped
    scalars (e.g. Adam's ``count``, shape ``[N_old]``) are re-tiled from
    row 0; error-feedback residual buffers (``[N_old, Lp_old]``) are
    mass-preserving: the old per-rank residuals are summed — the total
    untransmitted gradient mass — and spread evenly over the new ranks.
    Leaves without a leading rank dim pass through untouched.

    Bucketed (overlap) states — dict keys ``dtype#k`` from
    ``DistributedOptimizer(overlap=True)`` — reshard too: the bucket
    boundaries depend only on the leaf shapes and the bucket size (never
    on the world size), so the plan is re-derived from ``params`` and
    ``bucket_bytes`` (default: the ``HOROVOD_BUCKET_BYTES`` /
    ``HOROVOD_FUSION_THRESHOLD`` env resolution — reshard with the same
    knob the state was trained with; a mismatch raises instead of
    silently mis-slicing)."""
    from horovod_tpu.resilience import numerics as _numerics

    if isinstance(state, _numerics.NumericsGuardState):
        # numerics-guard wrapper: re-pack the inner (possibly sharded)
        # state; the guard's EWMA/loss-scale scalars are replicated and
        # world-size independent, so they ride through untouched. The
        # per-rank fingerprint vector is diagnostic, one step deep —
        # re-init it at the new size rather than inventing values for
        # ranks that have not stepped yet.
        n = int(to_size) if to_size is not None else basics.size()
        rank_norms = state.rank_norms
        if getattr(rank_norms, "shape", (0,)) != (n,):
            rank_norms = jnp.zeros((n,), jnp.float32)
        return state._replace(
            inner=reshard_optimizer_state(
                state.inner, params, to_size=to_size, axis=axis,
                bucket_bytes=bucket_bytes),
            rank_norms=rank_norms,
        )
    if isinstance(params, FsdpParams):
        # ZeRO-3: the pack metadata carries the leaf shapes AND the bucket
        # granularity the state was laid out with — reshard with the same
        # plan, no live param tree needed (reshard the shards themselves
        # with fsdp_reshard_params)
        if bucket_bytes is None:
            bucket_bytes = params.meta.bucket_bytes
        params = [
            jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
            for s, d in zip(params.meta.shapes, params.meta.dtypes)
        ]
    n_new = int(to_size) if to_size is not None else basics.size()
    ax = _C._axis(axis) if basics.is_initialized() else axis
    leaves = jax.tree_util.tree_leaves(params)
    # the true flat length per dtype group is n-independent (padding is not)
    lengths = {k: e[3] for k, e in _zero_spec(leaves, max(n_new, 1)).items()}
    is_ef = isinstance(state, (_EFState, _PowerSGDState))
    inner = state.inner if is_ef else state

    def _dict_str_keys(tree) -> set:
        keys: set = set()
        stack = [tree]
        while stack:
            node = stack.pop()
            if isinstance(node, dict):
                for k, v in node.items():
                    if isinstance(k, str):
                        keys.add(k)
                    stack.append(v)
            elif isinstance(node, (list, tuple)):  # NamedTuples included
                stack.extend(node)
        return keys

    def _is_bucket_key(k) -> bool:
        """Exactly the generated `dtype#index` form — a user param tree
        whose names merely contain '#' must NOT trip bucket handling
        (reshard stays safe on arbitrary plain states)."""
        if not isinstance(k, str) or "#" not in k:
            return False
        dt, _, idx = k.rpartition("#")
        if not idx.isdigit():
            return False
        try:
            jnp.dtype(dt)
        except TypeError:
            return False
        return True

    # bucketed (overlap) states carry `dtype#k` group keys: re-derive the
    # bucket plan (boundaries are n-independent) and validate the keys
    group_keys = {k for k in _dict_str_keys(inner) if _is_bucket_key(k)}
    if is_ef and isinstance(state.residual, dict):
        group_keys |= {
            k for k in state.residual if _is_bucket_key(k)
        }
    if group_keys:
        plan = _ov.plan_for(
            leaves, max(n_new, 1),
            bucket_bytes or _ov.bucket_bytes_from_env())
        exact = {b.key: b.L for b in plan.buckets}
        unknown = sorted(group_keys - set(exact))

        def _bucket_mismatch(detail):
            raise ValueError(
                "bucketed (overlap) optimizer state does not match the "
                f"re-derived BucketPlan ({detail}); reshard with the "
                "SAME HOROVOD_BUCKET_BYTES (or pass bucket_bytes=) the "
                "state was trained with"
            )

        if unknown:
            _bucket_mismatch(f"unknown bucket keys {unknown}")
        # a plan rebuilt with the wrong bucket size can still COVER the
        # state's keys (fewer, larger buckets subset finer ones) — pin
        # every bucket-keyed 2-D buffer's row length to the re-derived
        # bucket's padded length (residuals: Lp; shard buffers: Lp/n)
        for tree in (inner, state.residual if is_ef else None):
            if tree is None:
                continue
            for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
                gk = [
                    getattr(p, "key", None) for p in path
                    if _is_bucket_key(getattr(p, "key", None))
                ]
                if not gk or getattr(leaf, "ndim", 0) != 2:
                    continue
                L = exact[gk[-1]]
                rows = leaf.shape[0]
                Lp_old = L + ((-L) % rows)
                if leaf.shape[1] not in (Lp_old, Lp_old // rows):
                    _bucket_mismatch(
                        f"buffer {gk[-1]} has row length {leaf.shape[1]}, "
                        f"expected {Lp_old} or {Lp_old // rows}")
        cands: dict = {}
        for b in plan.buckets:
            cands.setdefault(b.dtype, []).append(b.L)
    else:
        exact = dict(lengths)
        cands = {dt: [L] for dt, L in lengths.items()}

    def _match_shard(x) -> Optional[tuple]:
        """(n_old, L) when `x` is a [n_old, shard] flat buffer of one of
        this param tree's packing groups, else None."""
        shape = tuple(getattr(x, "shape", ()))
        if len(shape) != 2:
            return None
        n_old, s_old = shape
        if n_old < 1:
            return None
        matches = [
            L for L in cands.get(str(_leaf_dtype(x)), ())
            if n_old * s_old == L + ((-L) % n_old)
        ]
        if not matches:
            return None
        unpadded = [L for L in matches if L == n_old * s_old]
        return n_old, (unpadded[0] if unpadded else max(matches))

    # Infer the source world size from the actual shard buffers. A state
    # with none is not a sharded state from this param tree — pass it
    # through untouched (consolidate_opt_state must be safe on plain
    # optimizer states, whose 1-D moment leaves would otherwise be
    # misread as per-rank vmapped scalars).
    olds = {
        m[0] for m in (
            _match_shard(x) for x in jax.tree_util.tree_leaves(inner)
        ) if m is not None
    }
    if not olds and is_ef \
            and isinstance(state.residual, dict) and state.residual:
        # stateless inner (e.g. plain sgd): the sharded signature lives in
        # the residual dict — group-string keys, [n_old, pad(L, n_old)]
        # rows. A replicated-path _EFState carries a param-tree residual
        # instead and never matches.
        if all(
            isinstance(k, str) and k in exact
            and getattr(v, "ndim", 0) == 2 and v.shape[0] >= 1
            and v.shape[1] == exact[k] + ((-exact[k]) % v.shape[0])
            for k, v in state.residual.items()
        ):
            olds = {v.shape[0] for v in state.residual.values()}
    if not olds:
        return state
    n_old_global = max(olds)
    if n_old_global == n_new and len(olds) == 1:
        return state  # same world size: a strict no-op, residuals included

    def _repad(flat, L):
        Lp_new = L + ((-L) % n_new)
        if Lp_new > L:
            flat = jnp.concatenate(
                [flat, jnp.zeros((Lp_new - L,), flat.dtype)])
        return flat

    def _path_group_key(path) -> Optional[str]:
        """The innermost dict key along `path` that names a packing
        group — authoritative for the buffer's true length, where the
        shape-based `_match_shard` can be ambiguous (a tail bucket whose
        ZeRO padding makes it the same padded size as a sibling)."""
        key = None
        for p in path:
            k = getattr(p, "key", None)
            if isinstance(k, str) and k in exact:
                key = k
        return key

    def one(path, x):
        shape = tuple(getattr(x, "shape", ()))
        gk = _path_group_key(path)
        if gk is not None and len(shape) == 2 and shape[0] >= 1:
            L = exact[gk]
            n_old = shape[0]
            if n_old * shape[1] == L + ((-L) % n_old):
                if n_old == n_new:
                    return x
                flat = jnp.asarray(x).reshape(-1)[:L]
                return _repad(flat, L).reshape(n_new, -1)
        m = _match_shard(x)
        if m is not None:
            n_old, L = m
            if n_old == n_new:
                return x
            flat = jnp.asarray(x).reshape(-1)[:L]
            return _repad(flat, L).reshape(n_new, -1)
        if len(shape) == 1 and shape[0] == n_old_global:
            # per-rank vmapped scalar (identical across ranks by
            # construction, e.g. Adam's count): re-tile from row 0
            if shape[0] == n_new:
                return x
            return jnp.broadcast_to(jnp.asarray(x)[0], (n_new,))
        return x

    def one_residual(x, key=None):
        # [n_old, Lp_old] per-rank full residuals: the summed rows are the
        # total untransmitted gradient mass; spread it evenly so the next
        # steps transmit exactly what the old ranks still owed
        L = exact.get(key, lengths.get(str(_leaf_dtype(x)), x.shape[1]))
        total = jnp.asarray(x).sum(axis=0)[:L] / n_new
        return jnp.broadcast_to(_repad(total, L), (n_new, L + ((-L) % n_new)))

    def one_q(x):
        # warm-start Q factors, tiled [n_old, m, r] with identical rows
        # (each comes out of an allreduce): re-tile row 0 for the new size
        if x is None:
            return None
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == n_old_global:
            if x.shape[0] == n_new:
                return x
            return jnp.broadcast_to(jnp.asarray(x)[0], (n_new,) + x.shape[1:])
        return x

    if isinstance(state, _PowerSGDState):
        out = _PowerSGDState(
            jax.tree_util.tree_map_with_path(one, state.inner),
            {k: one_residual(v, k) for k, v in state.residual.items()},
            jax.tree_util.tree_map(one_q, state.q, is_leaf=_q_is_leaf),
        )
    elif isinstance(state, _EFState):
        out = _EFState(
            jax.tree_util.tree_map_with_path(one, state.inner),
            {k: one_residual(v, k) for k, v in state.residual.items()},
        )
    else:
        out = jax.tree_util.tree_map_with_path(one, state)
    return _maybe_place_sharded(out, ax) if basics.is_initialized() else out


def _powersgd_update(grads, state, params, *, optimizer, compression, op,
                     ax, extra):
    """Replicated-state PowerSGD update (the non-ZeRO path): every >=2-D
    float leaf syncs rank-r P/Q factors with warm-started Q and the EF
    residual in the param-tree layout; 1-D float leaves ride the int8
    wire; integer/16-bit leaves pass through uncompressed. Works in all
    three dispatch modes of the plain optimizer: bound (inside shard_map —
    explicit P/Q allreduces), traced-unbound (replicated semantics), and
    eager (stacked ``[N, ...]`` or replicated leaves)."""
    n = _C._axis_size(ax)
    fallback = getattr(compression, "fallback", Int8Compressor)
    block = int(getattr(compression, "block", 0) or 0)
    inner, residual, q_tree = state.inner, state.residual, state.q
    g_leaves, treedef = jax.tree_util.tree_flatten(grads)
    r_leaves = jax.tree_util.tree_flatten(residual)[0]
    q_leaves = _q_leaves(q_tree)
    traced = any(_C._is_tracer(g) for g in g_leaves)
    bound = traced and _C._axis_bound(ax)

    reduced = [None] * len(g_leaves)
    new_res = [None] * len(g_leaves)
    new_q = [None] * len(g_leaves)
    wire_bytes = 0
    for i, g in enumerate(g_leaves):
        dt = _leaf_dtype(g)
        stacked = (not traced) and _C._is_stacked(g, ax)
        shape = tuple(g.shape[1:]) if stacked else tuple(
            getattr(g, "shape", ()))
        c = jnp.asarray(g) + r_leaves[i]
        # the residual itself may carry the per-rank axis after an earlier
        # stacked eager step; detect the layout on the corrected value
        per_rank = (
            not bound
            and getattr(c, "ndim", 0) == len(shape) + 1
            and c.shape[0] == n
            and tuple(c.shape[1:]) == shape
        )
        wire_bytes += _wire_bytes_leaf(shape, dt, compression)
        if q_leaves[i] is not None:
            qmat = q_leaves[i]
            if bound:
                m2d = c.reshape(shape[0], -1)
                approx, qn = _psgd_factor_sync(
                    m2d, qmat, lambda x: allreduce(x, Average, axis=ax))
                new_res[i] = (m2d - approx).reshape(shape)
                red = approx.reshape(shape)
            else:
                m2d = (c.mean(axis=0) if per_rank else c).reshape(
                    shape[0], -1)
                approx, qn = _psgd_factor_sync(m2d, qmat, lambda x: x)
                red = approx.reshape(shape)
                new_res[i] = c - (red[None] if per_rank else red)
            new_q[i] = qn
            reduced[i] = red * n if op == Sum else red
        elif _quantizable(dt):
            if bound:
                rt = int8_roundtrip(c, block)
                new_res[i] = c - rt
                reduced[i] = allreduce(c, op, axis=ax, compression=fallback)
            else:
                if per_rank:
                    rt = jax.vmap(lambda v: int8_roundtrip(v, block))(c)
                    red = rt.mean(axis=0)
                else:
                    rt = int8_roundtrip(c, block)
                    red = rt
                new_res[i] = c - rt
                reduced[i] = red * n if op == Sum else red
        else:
            new_res[i] = jnp.zeros_like(c)
            if bound:
                reduced[i] = allreduce(c, op, axis=ax)
            elif per_rank:
                red = c.sum(axis=0) if op == Sum else _C._div(c.sum(axis=0), n)
                reduced[i] = red.astype(dt)
            else:
                reduced[i] = c * n if op == Sum else c

    if basics.is_initialized():
        _record_sync_bytes("allreduce", n, wire_bytes)
    reduced_tree = jax.tree_util.tree_unflatten(treedef, reduced)
    updates, new_inner = optimizer.update(reduced_tree, inner, params, **extra)
    return updates, _PowerSGDState(
        new_inner,
        jax.tree_util.tree_unflatten(treedef, new_res),
        jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(q_tree, is_leaf=_q_is_leaf), new_q),
    )


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    op: ReduceOp = Average,
    compression=None,
    backward_passes_per_step: int = 1,
    axis: Optional[str] = None,
    gradient_predivide_factor: float = 1.0,
    error_feedback: bool = False,
    shard_optimizer: Optional[bool] = None,
    shard_params: Optional[bool] = None,
    overlap: Optional[bool] = None,
    bucket_bytes: Optional[int] = None,
    numerics_guard: Optional[bool] = None,
    loss_scale=None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so each ``update`` first allreduces gradients
    across ranks (reference ``_DistributedOptimizer.compute_gradients``,
    ``tensorflow/__init__.py:270-315``; torch hook-based variant
    ``torch/__init__.py:67-222``).

    ``backward_passes_per_step > 1`` accumulates that many gradient
    applications locally before communicating (reference
    ``torch/__init__.py:72-96``) via ``optax.MultiSteps``.

    ``gradient_predivide_factor`` splits the averaging divisor between
    pre/post-scale as the reference does for numerical headroom
    (upstream semantics: pre-divide by f, post-divide by size/f).

    ``compression`` defaults to the env spelling
    (``HOROVOD_COMPRESSION=none|fp16|int8|powersgd``) when not passed.
    Beyond fp16, ``Compression.int8`` rides the overflow-safe quantized
    ring (int8 + bf16 blockwise scales on the wire, f32 accumulation) and
    ``Compression.powersgd(r)`` syncs only rank-r P/Q factors per >=2-D
    leaf with the warm-started Q carried in this optimizer's state
    (requires ``error_feedback=True``; 1-D leaves fall back to int8).

    ``error_feedback=True`` (beyond the reference; EF-SGD, Karimireddy et
    al. 2019) makes lossy ``compression`` convergence-safe: each rank keeps
    the rounding error the compressor discarded and adds it back into the
    next step's gradient, so systematic bias (components smaller than a
    bfloat16 ULP vanishing every step) accumulates until it transmits
    instead of being lost. All elementwise — XLA fuses it into the step.
    Requires a lossy compressor; pair with Average/Sum (Adasum's scalar
    projections would mix into the residual bookkeeping).

    ``shard_optimizer=True`` (env ``HOROVOD_SHARD_OPTIMIZER=1``) switches
    the exchange to the ZeRO-1 decomposition: the gradient tree is
    flat-packed per dtype, reduce-scattered so each rank owns a 1/N shard,
    the inner update runs on only that shard's moments, and the update
    shards are all-gathered back — gradient-sync bytes halve
    (``(N-1)/N·B`` vs the allreduce ring's ``2(N-1)/N·B``) and
    optimizer-state HBM drops by N. The state pytree changes shape: every
    leaf carries a leading rank axis (``init`` on 8 ranks gives Adam
    moments ``[8, ceil(P/8)]`` per dtype). Use with
    ``make_shardmap_train_step(..., shard_optimizer=True)`` (which specs
    the state ``P(data)``), plain global jit (the layout does the
    sharding), or eagerly. Single-controller SPMD only; composes with
    ``compression`` and ``error_feedback`` (residuals ride the same flat
    packing); not with ``op=Adasum``.

    ``shard_params=True`` (env ``HOROVOD_SHARD_PARAMS=1``) is the ZeRO-3
    extension of ``shard_optimizer``: the PARAMETERS are sharded too.
    ``init`` takes the packed shards from :func:`fsdp_pack_params`
    (raising on a plain tree) and builds the same ``[N, shard]`` state
    layout as ZeRO-1; ``update`` takes :class:`FsdpParams` gradient
    shards — produced for free by differentiating the loss through
    :func:`fsdp_gather_params` (the gather's transpose reduce-scatters)
    — divides for ``Average``, vmaps the inner update per shard, and
    returns update shards with NO trailing all-gather: params stay
    sharded, and the next step's gather-on-use re-materializes them
    (``make_shardmap_train_step(shard_params=True)`` wires all of this).
    Per-chip param + optimizer HBM both drop by N; the wire cost is the
    per-step parameter gather, twice (forward + the ``jax.checkpoint``
    backward re-gather) — ``HOROVOD_FSDP_WIRE=int8`` quantizes that leg.
    The gradient leg is exact by construction, so gradient
    ``compression``/``error_feedback`` are rejected (nothing lossy to
    feed back); ``op`` must be Average/Sum and the numerics guard does
    not compose yet (its global-norm reduction assumes full gradients).
    ``bucket_bytes`` must match the value given to ``fsdp_pack_params``
    — the pack defines the exchange granularity.

    ``overlap=True`` (env ``HOROVOD_OVERLAP=1``; implied by
    ``bucket_bytes=``) switches the gradient exchange to **bucketed
    backward-pass sync** — the reference's fusion-buffer overlap trick,
    TPU-native: the flat per-dtype packing is partitioned into
    ~``bucket_bytes`` (``HOROVOD_BUCKET_BYTES``, default 64 MB, honoring
    ``HOROVOD_FUSION_THRESHOLD``) buckets in reverse-topological
    (backprop-emission) order, and ONE collective is issued per bucket
    instead of one per tree/dtype. Each bucket's
    ``psum``/``psum_scatter`` depends only on its own leaves'
    cotangents, so XLA's latency-hiding scheduler (pin the flags with
    :func:`horovod_tpu.tuning.apply_xla_flags`) launches it while the
    remaining backward still runs — step time approaches
    ``max(compute, comm)`` instead of ``compute + comm``. Composes with
    ``shard_optimizer=True`` (per-bucket reduce-scatter, state buffers
    ``[N, shard_k]`` per bucket, a single trailing all-gather per dtype)
    and the fp16/int8 wire formats (per-bucket compress; error-feedback
    residuals keyed by bucket). Trajectories are bit-identical to the
    monolithic path for none/fp16 (packing is a permutation and the
    elementwise wire commutes with it); int8's blockwise scales are
    layout-dependent, so that wire tracks within one quantization step
    per element (EF keeps it convergence-safe). Not with ``op=Adasum``
    or PowerSGD (per-tensor/per-leaf math that bucket packing would
    mix).

    ``numerics_guard=True`` (env ``HOROVOD_NUMERICS_GUARD=1``; implied by
    ``loss_scale``) wraps the whole optimizer in the in-jit numerics
    guard (:func:`horovod_tpu.resilience.numerics.guard`): every step's
    gradient finiteness + EWMA global-norm spike verdict is computed in
    one fused reduction inside the step, and a BAD step's update —
    moments, EF residuals, PowerSGD ``Q`` warm-starts — is discarded
    atomically. ``loss_scale`` enables dynamic bf16/fp16 loss scaling
    (``"dynamic"`` or an initial float; grow/backoff carried in the guard
    state). The ``make_*_train_step`` builders detect the guard and
    thread the loss + scale automatically.
    """
    if shard_optimizer is None:
        shard_optimizer = _env_true("HOROVOD_SHARD_OPTIMIZER")
    if shard_params is None:
        shard_params = _env_true("HOROVOD_SHARD_PARAMS")
    ov_bytes = _ov.resolve_bucket_bytes(overlap, bucket_bytes)
    if compression is None:
        # unset -> the env spelling (HOROVOD_COMPRESSION=fp16|int8|powersgd)
        compression = Compression.from_env()
        if getattr(compression, "factorized", False) and not error_feedback:
            # the env knob must work on call sites that never opted into
            # compression kwargs: env-resolved PowerSGD implies the error
            # feedback it cannot converge without
            error_feedback = True
    factorized = getattr(compression, "factorized", False)
    quantized = getattr(compression, "quantized", False)
    if shard_params:
        if op not in (Average, Sum):
            raise ValueError(
                "shard_params=True (ZeRO-3) supports op=Average/Sum only "
                "(Adasum's pairwise projections have no reduce-scatter "
                "formulation)"
            )
        if compression is not Compression.none:
            raise ValueError(
                "gradient compression does not compose with "
                "shard_params=True: the ZeRO-3 gradient leg is the "
                "parameter gather's transpose — exact full precision by "
                "construction. Compress the parameter GATHER instead "
                "(HOROVOD_FSDP_WIRE=int8)"
            )
        if error_feedback:
            raise ValueError(
                "error_feedback needs a lossy gradient wire; the ZeRO-3 "
                "gradient leg is exact (see shard_params). The int8 "
                "GATHER wire perturbs only forward parameter values — "
                "there is no gradient rounding to feed back"
            )
        if gradient_predivide_factor != 1.0:
            raise ValueError(
                "gradient_predivide_factor is not supported with "
                "shard_params=True (the reduced shards arrive through "
                "the gather transpose; there is no pre-wire scale point)"
            )
    if factorized and not error_feedback:
        raise ValueError(
            "PowerSGD compression is biased low-rank truncation; it is "
            "only convergence-safe with error_feedback=True (EF-SGD, "
            "Karimireddy et al. 2019)"
        )
    if factorized and op not in (Average, Sum):
        raise ValueError("PowerSGD compression supports op=Average/Sum only")
    if (factorized or quantized) and gradient_predivide_factor != 1.0:
        raise ValueError(
            "gradient_predivide_factor is a headroom trick for plain "
            "16-bit casts; blockwise int8 scaling / PowerSGD factors "
            "normalize per block and do not support it"
        )
    if error_feedback and compression is Compression.none:
        raise ValueError(
            "error_feedback=True needs a lossy compression "
            "(e.g. Compression.fp16); with Compression.none there is no "
            "rounding error to feed back"
        )
    if error_feedback and op == Adasum:
        raise ValueError("error_feedback is not supported with op=Adasum")
    if quantized and op == Adasum:
        raise ValueError(
            "quantized compression is not supported with op=Adasum (the "
            "scalar projections have no low-bit reduction formulation)"
        )
    if shard_optimizer and op == Adasum:
        raise ValueError(
            "shard_optimizer=True is not supported with op=Adasum (the "
            "pairwise projections have no reduce-scatter formulation)"
        )
    if ov_bytes and factorized:
        raise ValueError(
            "overlap/bucket_bytes is not supported with PowerSGD "
            "compression: the rank-r P/Q factors are per-leaf matrices "
            "that bucket packing would mix; use the int8/fp16 wire with "
            "overlap, or PowerSGD without it"
        )
    if ov_bytes and op == Adasum:
        raise ValueError(
            "overlap/bucket_bytes is not supported with op=Adasum (the "
            "pairwise projections are per-tensor scalars; bucket packing "
            "would mix them)"
        )

    def _allreduce_grads(grads):
        if op == Adasum and compression is Compression.none:
            return _fused_adasum_tree(grads, axis)

        def one(g):
            if op == Average and gradient_predivide_factor != 1.0:
                g = g / gradient_predivide_factor
                out = allreduce(g, Sum, axis=axis, compression=compression)
                return out * (gradient_predivide_factor / basics.size())
            return allreduce(g, op, axis=axis, compression=compression)

        if op != Adasum and basics.is_initialized():
            ax = _C._axis(axis)
            _record_sync_bytes(
                "allreduce", _C._axis_size(ax),
                _tree_sync_wire_bytes(grads, compression, axis=ax),
            )
        return jax.tree_util.tree_map(one, grads)

    def _roundtrip(g):
        """The value g effectively contributes through the wire. With a
        predivide the wire carries compress(g/f) (scaled back by f at the
        receiver), so the residual must be measured against THAT — rounding
        introduced by the divide is exactly the bias EF exists to track."""
        if op == Average and gradient_predivide_factor != 1.0:
            c, ctx = compression.compress(g / gradient_predivide_factor)
            return compression.decompress(c, ctx) * gradient_predivide_factor
        c, ctx = compression.compress(g)
        return compression.decompress(c, ctx)

    def init_fn(params):
        if shard_params:
            if not isinstance(params, FsdpParams):
                raise TypeError(
                    "DistributedOptimizer(shard_params=True).init expects "
                    "the packed FsdpParams shards — build them with "
                    "fsdp_pack_params(params) (and gather back with "
                    "fsdp_unpack_params)"
                )
            if ov_bytes and params.meta.bucket_bytes != ov_bytes:
                raise ValueError(
                    "bucket_bytes mismatch: params were packed with "
                    f"bucket_bytes={params.meta.bucket_bytes} but this "
                    f"optimizer resolved {ov_bytes}; pass the same value "
                    "to fsdp_pack_params — the pack defines the exchange "
                    "granularity"
                )
            state = jax.vmap(optimizer.init)(params.shards)
            return _maybe_place_sharded(state, _C._axis(axis))
        if shard_optimizer:
            ax = _C._axis(axis)
            state = _zero_init(
                optimizer, params, _C._axis_size(ax),
                error_feedback=error_feedback,
                compression=compression if factorized else None,
                bucket_bytes=ov_bytes,
            )
            return _maybe_place_sharded(state, ax)
        inner = optimizer.init(params)
        if factorized:
            residual = jax.tree_util.tree_map(jax.numpy.zeros_like, params)
            return _PowerSGDState(
                inner, residual, _powersgd_q_init(params, compression))
        if error_feedback:
            if ov_bytes:
                # overlap: error-feedback residuals keyed by bucket — the
                # flat layout each bucket's wire roundtrip is measured in
                plan = _ov.plan_for(
                    jax.tree_util.tree_leaves(params), 1, ov_bytes)
                residual = {
                    b.key: jnp.zeros((b.L,), dtype=jnp.dtype(b.dtype))
                    for b in plan.buckets
                }
            else:
                residual = jax.tree_util.tree_map(
                    jax.numpy.zeros_like, params)
            return _EFState(inner, residual)
        return inner

    def update_fn(grads, state, params=None, **extra):
        if shard_params:
            return _fsdp_update(
                grads, state, params,
                optimizer=optimizer, op=op, ax=_C._axis(axis), extra=extra,
            )
        if shard_optimizer:
            return _zero_update(
                grads, state, params,
                optimizer=optimizer, compression=compression,
                error_feedback=error_feedback, op=op,
                predivide=gradient_predivide_factor, ax=_C._axis(axis),
                roundtrip=_roundtrip, extra=extra,
                bucket_bytes=ov_bytes,
            )
        if factorized:
            return _powersgd_update(
                grads, state, params, optimizer=optimizer,
                compression=compression, op=op, ax=_C._axis(axis),
                extra=extra,
            )
        if ov_bytes:
            # non-sharded overlap: K bucket allreduces (reverse emission
            # order), each depending only on its own leaves' cotangents;
            # EF residuals ride the bucket-keyed flat layout
            reduced, new_res = _ov.bucketed_allreduce(
                grads, op, axis=axis, compression=compression,
                bucket_bytes=ov_bytes,
                predivide=gradient_predivide_factor,
                residual=state.residual if error_feedback else None,
                roundtrip=_roundtrip,
            )
            if error_feedback:
                updates, inner = optimizer.update(
                    reduced, state.inner, params, **extra)
                return updates, _EFState(inner, new_res)
            return optimizer.update(reduced, state, params, **extra)
        if error_feedback:
            corrected = jax.tree_util.tree_map(
                lambda g, r: g + r, grads, state.residual
            )
            # residual = what the wire will round away; the allreduce below
            # compresses `corrected` itself (single compression pass), which
            # is exactly the transform _roundtrip models
            residual = jax.tree_util.tree_map(
                lambda c: c - _roundtrip(c), corrected
            )
            reduced = _allreduce_grads(corrected)
            updates, inner = optimizer.update(
                reduced, state.inner, params, **extra
            )
            return updates, _EFState(inner, residual)
        grads = _allreduce_grads(grads)
        return optimizer.update(grads, state, params, **extra)

    tx = optax.GradientTransformationExtraArgs(init_fn, update_fn)
    if backward_passes_per_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)
    if numerics_guard is None:
        numerics_guard = (
            _env_true("HOROVOD_NUMERICS_GUARD") or loss_scale is not None
        )
    elif not numerics_guard and loss_scale is not None:
        raise ValueError(
            "loss_scale is carried in the numerics guard's state (the "
            "guard unscales the gradients and backs the scale off on bad "
            "steps); numerics_guard=False with loss_scale set would "
            "silently train UNSCALED — drop loss_scale or the explicit "
            "numerics_guard=False"
        )
    if numerics_guard and shard_params:
        raise ValueError(
            "numerics_guard does not compose with shard_params=True yet: "
            "the guard's fused global-norm/finiteness reduction assumes "
            "full (or ZeRO-1 replicated) gradients, and per-rank verdicts "
            "over FsdpParams shards could diverge. Guard ZeRO-1 "
            "(shard_optimizer=True) instead, or train ZeRO-3 unguarded"
        )
    if numerics_guard:
        # outermost, so a BAD verdict freezes EVERYTHING this optimizer
        # owns — inner moments, EF residuals, PowerSGD Q, MultiSteps
        # accumulators — in one atomic where-select
        from horovod_tpu.resilience import numerics as _numerics

        tx = _numerics.guard(tx, loss_scale=loss_scale, axis=axis)
    return tx


class DistributedGradientTape:
    """Analog of ``hvd.DistributedGradientTape`` (reference
    ``tensorflow/__init__.py:478-535``): wraps a gradient-producing function
    (e.g. ``jax.grad(loss)`` or ``jax.value_and_grad(loss)``) so its gradients
    are allreduced.

    Example::

        tape = hvd.DistributedGradientTape(jax.value_and_grad(loss_fn))
        (loss, grads) = tape(params, batch)   # grads are rank-averaged
    """

    def __init__(
        self,
        grad_fn: Callable,
        *,
        op: ReduceOp = Average,
        compression=Compression.none,
        axis: Optional[str] = None,
        has_aux_value: Optional[bool] = None,
    ):
        self._fn = grad_fn
        self._op = op
        self._compression = compression
        self._axis = axis
        self._has_aux_value = has_aux_value

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        has_value = self._has_aux_value
        if has_value is None:
            # value_and_grad returns (scalar_loss, grads). Require the first
            # element to actually look like a scalar loss so a 2-tuple of
            # gradients (jax.grad with argnums=(0, 1)) is not misclassified;
            # pass has_aux_value explicitly for ambiguous cases.
            has_value = (
                isinstance(out, tuple)
                and len(out) == 2
                and not isinstance(out[0], (list, dict))
                and getattr(out[0], "ndim", None) == 0
            )
        if has_value:
            value, grads = out
        else:
            grads = out
        if self._op == Adasum and self._compression is Compression.none:
            grads = _fused_adasum_tree(grads, self._axis)
        else:
            grads = jax.tree_util.tree_map(
                lambda g: allreduce(
                    g, self._op, axis=self._axis,
                    compression=self._compression,
                ),
                grads,
            )
        self._record(grads)
        return (value, grads) if has_value else grads

    @staticmethod
    def _record(grads):
        """Per-step accounting for the tape path. Eager calls only: under
        jit this __call__ body runs once at trace time, so recording there
        would freeze a single count into the compiled step."""
        if not _metrics.enabled():
            return
        leaves = jax.tree_util.tree_leaves(grads)
        if any(isinstance(g, jax.core.Tracer) for g in leaves):
            return
        _metrics.counter(
            "tape_steps", help="DistributedGradientTape gradient exchanges"
        ).inc()
        _metrics.counter(
            "tape_grad_bytes", help="gradient bytes exchanged by the tape"
        ).inc(sum(getattr(g, "nbytes", 0) or 0 for g in leaves))


def broadcast_parameters(params: Any, root_rank: int = 0, *, axis=None):
    """Broadcast a pytree of parameters from root (reference
    ``torch/__init__.py:451-469``, ``tensorflow/__init__.py:126-152``
    ``broadcast_variables``). Under single-controller SPMD parameters are
    born synchronized; this is the multi-process resync primitive and the
    checkpoint-restore pattern (SURVEY.md §5.4)."""
    _metrics.counter(
        "broadcast_parameters_calls",
        help="parameter-tree broadcasts (init sync / checkpoint restore)",
    ).inc()
    return jax.tree_util.tree_map(
        lambda p: broadcast(p, root_rank, axis=axis)
        if isinstance(p, (jax.Array,)) or hasattr(p, "dtype")
        else broadcast_object(p, root_rank),
        params,
    )


broadcast_variables = broadcast_parameters


def is_sharded_state_leaf(x, *, axis=None) -> bool:
    """Is `x` a ZeRO-1 sharded optimizer-state leaf (leading rank dim laid
    out over the data axis)? Such leaves are per-rank data: broadcasting
    root's value over them would blow each rank's 1/N moment shard back up
    to root's copy and destroy the sharding."""
    ax = _C._axis(axis)
    return hasattr(x, "sharding") and _C._is_stacked(x, ax)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0, *, axis=None):
    """Broadcast optimizer state (reference ``torch/__init__.py:471-607``:
    scalars are wrapped into tensors and broadcast; here the optax state is
    already a pytree of arrays/scalars).

    Leaves sharded over the data axis (ZeRO-1 moment shards, see
    ``DistributedOptimizer(shard_optimizer=True)``) are detected and left
    in place: each rank's shard IS its own authoritative state, and
    stuffing root's row into every rank would both corrupt the other
    ranks' moments and re-replicate the very state the sharding un-replicated.
    """
    ax = _C._axis(axis)
    skipped = [0]

    def one(x):
        if is_sharded_state_leaf(x, axis=ax):
            skipped[0] += 1
            return x
        if isinstance(x, (jax.Array,)) or hasattr(x, "dtype"):
            return broadcast(x, root_rank, axis=ax)
        return broadcast_object(x, root_rank)

    out = jax.tree_util.tree_map(one, opt_state)
    if skipped[0]:
        _metrics.counter(
            "broadcast_optimizer_state_sharded_skipped",
            help="ZeRO-1 sharded state leaves left un-broadcast",
        ).inc(skipped[0])
    return out
