"""Keras callbacks (reference ``horovod/_keras/callbacks.py``, re-exported by
``horovod/keras/callbacks.py`` and ``horovod/tensorflow/keras/callbacks.py``):

- :class:`BroadcastGlobalVariablesCallback` — sync weights + optimizer state
  from root after the first batch (reference ``_keras/callbacks.py:22-46``).
- :class:`MetricAverageCallback` — average epoch metrics across ranks
  (reference ``_keras/callbacks.py:48-87``).
- :class:`LearningRateScheduleCallback` — multiply the LR by a (possibly
  epoch-dependent) factor over an epoch range (reference
  ``_keras/callbacks.py:90-160``).
- :class:`LearningRateWarmupCallback` — ramp LR from lr/size to lr over the
  first epochs, the "Accurate Large Minibatch SGD" gradual warmup (reference
  ``_keras/callbacks.py:163-192``).
"""

from __future__ import annotations

import keras
import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast all variables from root rank at the start of training
    (reference ``_keras/callbacks.py:22-46``: fires once, after the first
    batch, so lazily-built optimizer slots exist on every rank)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        if hvd.size() > 1:
            hvd.broadcast_variables(self.model.weights, self.root_rank)
            if getattr(self.model, "optimizer", None) is not None:
                hvd.broadcast_variables(
                    self.model.optimizer.variables, self.root_rank
                )
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metrics over ranks before they reach other callbacks
    (checkpointers, LR schedulers, loggers) — reference
    ``_keras/callbacks.py:48-87``. Order this callback before any consumer."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or hvd.size() == 1:
            return
        for k, v in list(logs.items()):
            arr = np.asarray(v, dtype=np.float32)
            avg = np.asarray(hvd.allreduce(
                tf.convert_to_tensor(arr), hvd.Average, name=f"metric.{k}"
            ))
            logs[k] = float(avg) if np.ndim(v) == 0 else avg


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Scale the optimizer LR by ``multiplier`` within ``[start_epoch,
    end_epoch)`` (reference ``_keras/callbacks.py:90-160``). ``multiplier``
    may be a constant or a function of epoch; with ``staircase=False`` and
    ``steps_per_epoch`` set, the multiplier sees fractional epochs for smooth
    per-batch schedules. ``momentum_correction`` temporarily rescales momentum
    when the LR changes so the implied update velocity is preserved."""

    def __init__(self, multiplier, start_epoch: int = 0, end_epoch=None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch=None, initial_lr=None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = initial_lr
        self.restore_momentum = None
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = None
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _autodetect_steps_per_epoch(self):
        if self.steps_per_epoch is not None:
            return self.steps_per_epoch
        params = getattr(self, "params", None) or {}
        if params.get("steps"):
            return params["steps"]
        raise ValueError(
            "LearningRateScheduleCallback with staircase=False needs "
            "steps_per_epoch (could not autodetect from fit params)"
        )

    def _current_lr(self):
        return float(
            keras.ops.convert_to_numpy(self.model.optimizer.learning_rate)
        )

    def _set_lr(self, lr: float):
        self.model.optimizer.learning_rate = lr

    def _in_range(self, epoch) -> bool:
        return epoch >= self.start_epoch and (
            self.end_epoch is None or epoch < self.end_epoch
        )

    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            self.initial_lr = self._current_lr()
        if not self.staircase and self.steps_per_epoch is None:
            self.steps_per_epoch = self._autodetect_steps_per_epoch()

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            self._adjust_lr(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase and self._in_range(self.current_epoch):
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_lr(epoch)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            # log the LR keras-style so downstream callbacks see it
            logs["lr"] = self._current_lr()

    def _adjust_lr(self, epoch):
        old_lr = self._current_lr()
        new_lr = self.initial_lr * self.multiplier(epoch)
        self._set_lr(new_lr)
        opt = getattr(self.model, "optimizer", None)
        if (self.momentum_correction and opt is not None
                and isinstance(getattr(opt, "momentum", None),
                               keras.Variable)
                and old_lr > 0):
            # momentum correction (reference _keras/callbacks.py:129-143):
            # scale momentum by new_lr/old_lr for one step so velocity carries
            # over, then restore. Only possible when momentum is a backend
            # Variable — Keras 3's stock SGD stores it as a Python float that
            # gets baked into the traced step, where mutating it would either
            # not land or (worse) freeze the scaled value in permanently.
            self._restore_momentum_if_needed()
            self.restore_momentum = float(
                keras.ops.convert_to_numpy(opt.momentum)
            )
            opt.momentum.assign(self.restore_momentum * new_lr / old_lr)

    def _restore_momentum_if_needed(self):
        if self.restore_momentum is not None:
            self.model.optimizer.momentum.assign(self.restore_momentum)
            self.restore_momentum = None

    def on_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()


class MetricsCallback(keras.callbacks.Callback):
    """Keras spelling of :class:`horovod_tpu.callbacks.MetricsCallback`:
    every ``every_n_steps`` batches, print the horovod_tpu metrics-registry
    summary (or dump the JSON snapshot to ``dump_path``) on rank 0. The
    registry itself is fed by the instrumented collective/core layers; this
    callback only adds the fit-loop cadence counters."""

    def __init__(self, every_n_steps: int = 100, dump_path=None,
                 printer=print):
        super().__init__()
        self.every_n_steps = every_n_steps
        self.dump_path = dump_path
        self.printer = printer
        self._seen = 0

    def _emit(self):
        from horovod_tpu.observability import exporters

        try:
            if hvd.rank() != 0:
                return
        except RuntimeError:
            pass  # not initialized (single-machine debugging): emit anyway
        exporters.emit_snapshot(
            self.dump_path, self.printer,
            header=f"horovod_tpu metrics @ batch {self._seen}:\n",
        )

    def on_batch_end(self, batch, logs=None):
        from horovod_tpu.observability import metrics

        self._seen += 1
        if metrics.enabled():
            metrics.counter("fit_batches", help="fit batches run").inc()
        if self.every_n_steps and self._seen % self.every_n_steps == 0:
            self._emit()

    def on_train_end(self, logs=None):
        self._emit()


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup from ``initial_lr / size`` to ``initial_lr`` over
    ``warmup_epochs`` (reference ``_keras/callbacks.py:163-192``, after
    Goyal et al. 2017)."""

    def __init__(self, warmup_epochs: int = 5, momentum_correction: bool = True,
                 steps_per_epoch=None, verbose: int = 0, initial_lr=None):
        def multiplier(epoch):
            # epoch is fractional; ramp 1/size -> 1 across warmup_epochs
            return 1.0 / hvd.size() + epoch * (
                1.0 - 1.0 / hvd.size()) / warmup_epochs

        super().__init__(
            multiplier, start_epoch=0, end_epoch=warmup_epochs,
            staircase=False, momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch, initial_lr=initial_lr,
        )
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0:
            print(
                f"\nEpoch {epoch + 1}: finished gradual learning rate warmup "
                f"to {self._current_lr():.6g}."
            )
