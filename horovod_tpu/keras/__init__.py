"""Keras frontend: ``import horovod_tpu.keras as hvd``.

Reference parity with ``horovod/keras/__init__.py`` + the shared impl in
``horovod/_keras/__init__.py`` (0.19.2): ``DistributedOptimizer`` via a
dynamically-created optimizer subclass that aggregates gradients across ranks
before applying (reference ``_keras/__init__.py:20-78``), broadcast/metric/LR
callbacks (``_keras/callbacks.py``), and ``load_model`` that deserializes
checkpointed optimizers straight into distributed ones
(``keras/__init__.py:117-160``).

Targets Keras 3 (the in-image version); the reference's parallel
``horovod.keras`` vs ``horovod.tensorflow.keras`` stacks collapse into this
one module because Keras 3 is itself the unified stack.
"""

from __future__ import annotations

import keras
import numpy as np
import tensorflow as tf

from horovod_tpu.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, process_rank, process_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, gloo_enabled,
    nccl_built, mpi_built, gloo_built, ccl_built,
    ddl_built, xla_built,
)
import horovod_tpu.tensorflow as _hvd_tf
from horovod_tpu.tensorflow import (  # noqa: F401
    Adasum, Average, ReduceOp, Sum,
    allgather, allgather_object, alltoall, broadcast, broadcast_object, join,
)
from horovod_tpu.tensorflow.compression import Compression  # noqa: F401
from horovod_tpu.keras import callbacks  # noqa: F401
from horovod_tpu.keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    MetricAverageCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
)


def allreduce(value, op=Average, *, name=None, compression=Compression.none):
    """Allreduce of a tensor or numpy value (reference
    ``keras/__init__.py:82-95``)."""
    if isinstance(value, (np.ndarray, np.generic, float, int)):
        out = _hvd_tf.allreduce(tf.convert_to_tensor(value), op, name=name,
                                compression=compression)
        return out.numpy()
    return _hvd_tf.allreduce(value, op, name=name, compression=compression)


def broadcast_global_variables(root_rank: int = 0, model=None):
    """Broadcast a model's weights + optimizer state from root (reference
    ``keras/__init__.py:97-106``; TF2 has no global-variables collection, so
    the model is passed explicitly)."""
    if model is None:
        raise ValueError(
            "Keras 3 has no global-variables collection; pass model="
        )
    _hvd_tf.broadcast_variables(model.weights, root_rank)
    if getattr(model, "optimizer", None) is not None:
        _hvd_tf.broadcast_variables(model.optimizer.variables, root_rank)


class _DistributedOptimizerMixin:
    """Gradient-aggregating override mixed over the user's optimizer class
    (reference ``_keras/__init__.py:20-78``): every ``apply`` first allreduces
    the gradients across ranks. Keras 3 funnels both ``apply_gradients`` and
    ``apply`` through ``apply``, so this single override covers ``model.fit``
    and custom training loops."""

    _hvd_compression = Compression.none
    _hvd_sparse_as_dense = False
    _hvd_op = Average

    def _hvd_allreduce_grads(self, grads):
        return [
            g if g is None else _hvd_tf.allreduce(
                g, self._hvd_op, compression=self._hvd_compression,
                sparse_as_dense=self._hvd_sparse_as_dense,
            )
            for g in grads
        ]

    def apply(self, grads, trainable_variables=None):
        if size() > 1:
            grads = self._hvd_allreduce_grads(list(grads))
        return super().apply(grads, trainable_variables)


class _AdasumOptimizerMixin:
    """Delta-style Adasum override mixed over the user's optimizer class
    (semantics of reference ``tensorflow/__init__.py:317-411``, re-expressed
    for Keras 3): a ``delta_start`` stash per variable; every
    ``backward_passes_per_step``-th ``apply`` the locally-updated variables
    are turned into deltas, Adasum-combined across workers, and written back
    on top of the stash."""

    _hvd_compression = Compression.none
    _hvd_backward_passes = 1

    def build(self, variables):
        super().build(variables)
        self._hvd_starts = [
            self.add_variable_from_reference(v, name="delta_start")
            for v in variables
        ]
        for s, v in zip(self._hvd_starts, variables):
            s.assign(v)

    def _hvd_sync(self, tvars):
        for v, s in zip(tvars, self._hvd_starts):
            delta = tf.convert_to_tensor(v) - tf.convert_to_tensor(s)
            reduced = _hvd_tf.allreduce(
                delta, Adasum, compression=self._hvd_compression
            )
            s.assign_add(tf.cast(reduced, s.dtype))
            v.assign(s)
        return tf.constant(True)

    def apply(self, grads, trainable_variables=None):
        result = super().apply(grads, trainable_variables)
        tvars = (
            list(trainable_variables)
            if trainable_variables is not None
            else list(self._trainable_variables)
        )
        bpps = self._hvd_backward_passes
        if bpps == 1:
            self._hvd_sync(tvars)
        else:
            # self.iterations was just incremented by super().apply
            it = tf.cast(tf.convert_to_tensor(self.iterations), tf.int64)
            tf.cond(
                tf.equal(it % bpps, 0),
                lambda: self._hvd_sync(tvars),
                lambda: tf.constant(True),
            )
        return result


def create_distributed_optimizer(optimizer, *, compression=Compression.none,
                                 sparse_as_dense=False, op=Average,
                                 backward_passes_per_step: int = 1,
                                 name=None):
    """Dynamically subclass `optimizer` with distributed gradient aggregation
    (reference ``_keras/__init__.py:20-78``: ``cls = type(..., (Mixin, klass))``
    then ``from_config``). ``op=Adasum`` selects the delta-style mixin
    (reference ``tensorflow/__init__.py:317-411``), which also honors
    ``backward_passes_per_step``."""
    if op == Adasum:
        cls = type(
            name or optimizer.__class__.__name__,
            (_AdasumOptimizerMixin, optimizer.__class__),
            {},
        )
        opt = cls.from_config(optimizer.get_config())
        opt._hvd_compression = compression
        opt._hvd_backward_passes = max(1, int(backward_passes_per_step))
        return opt
    if backward_passes_per_step != 1:
        raise NotImplementedError(
            "backward_passes_per_step > 1 is the torch/optax frontends' "
            "feature; the reference's 0.19.2 Keras wrapper has no local "
            "gradient accumulation (_keras/__init__.py:20-78)"
        )
    cls = type(
        name or optimizer.__class__.__name__,
        (_DistributedOptimizerMixin, optimizer.__class__),
        {},
    )
    opt = cls.from_config(optimizer.get_config())
    opt._hvd_compression = compression
    opt._hvd_sparse_as_dense = sparse_as_dense
    opt._hvd_op = op
    return opt


DistributedOptimizer = create_distributed_optimizer


def _wrap_optimizer_class(klass, compression=Compression.none, op=Average):
    """A deserializable distributed subclass of `klass` (used by
    :func:`load_model`; reference ``keras/__init__.py:117-160``)."""
    cls = type(
        klass.__name__, (_DistributedOptimizerMixin, klass),
        {"_hvd_compression": compression, "_hvd_op": op},
    )
    return cls


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a saved model with its optimizer re-wrapped as a
    ``DistributedOptimizer`` (reference ``keras/__init__.py:117-160``).

    The reference shadows optimizer classes during deserialization; Keras 3
    resolves built-in classes by module path before consulting
    ``custom_objects`` (``keras/src/saving/serialization_lib.py``
    ``_retrieve_class_or_fn``), so built-ins are instead re-wrapped *after*
    load with their restored slot state transferred. ``custom_optimizers``
    classes (which do resolve through ``custom_objects``) are shadowed the
    reference's way."""
    horovod_objects = {}
    if custom_optimizers is not None:
        horovod_objects.update({
            klass.__name__: _wrap_optimizer_class(klass, compression)
            for klass in custom_optimizers
        })
    if custom_objects is not None:
        horovod_objects.update(custom_objects)
    model = keras.models.load_model(
        filepath, custom_objects=horovod_objects or None
    )
    opt = getattr(model, "optimizer", None)
    if opt is not None and not isinstance(opt, _DistributedOptimizerMixin):
        dist = create_distributed_optimizer(opt, compression=compression)
        if opt.built:
            dist.build(model.trainable_variables)
            for dst, src in zip(dist.variables, opt.variables):
                dst.assign(src)
        model.optimizer = dist
    return model
