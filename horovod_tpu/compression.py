"""Gradient compression (reference ``horovod/torch/compression.py:20-73``,
``horovod/tensorflow/compression.py``): compress before the collective, decompress
after. On TPU fp16 compression maps to bfloat16 — same 2-byte wire size, far
better dynamic range on the MXU, and XLA fuses the casts into the collective's
pack/unpack copies."""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface (reference ``torch/compression.py:20-31``)."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Casts float tensors to 16 bits for the wire (reference
    ``torch/compression.py:42-63``). bfloat16 rather than float16: TPU-native,
    no overflow scaling needed."""

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class Compression:
    """Namespace mirroring ``hvd.Compression`` (reference
    ``torch/compression.py:66-73``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
