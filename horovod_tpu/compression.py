"""Gradient compression (reference ``horovod/torch/compression.py:20-73``,
``horovod/tensorflow/compression.py``): compress before the collective, decompress
after. On TPU fp16 compression maps to bfloat16 — same 2-byte wire size, far
better dynamic range on the MXU, and XLA fuses the casts into the collective's
pack/unpack copies.

Beyond the reference's fp16 cap, two low-bit compressors (both pair with
``error_feedback=True`` on :class:`horovod_tpu.optim.DistributedOptimizer`,
which keeps EF-SGD convergence guarantees — Karimireddy et al., ICML 2019):

- :class:`Int8Compressor` (``Compression.int8``): blockwise-scaled int8 —
  one bf16 max-abs scale per :data:`INT8_BLOCK` elements, ~4x fewer wire
  bytes than fp32 (25.8% incl. scale overhead). The *reduction* of int8
  values widens to f32 per shard inside the collective kernels
  (:mod:`horovod_tpu.ops.collective`), so int8 never overflows in the ring.
- :class:`PowerSGDCompressor` (``Compression.powersgd(rank=r)``): rank-r
  low-rank factorization of >=2-D gradient leaves (Vogels et al., NeurIPS
  2019) — only the small P/Q factors travel; 1-D leaves fall back to int8.
  Stateful (warm-started Q lives in the optimizer state), so it rides
  ``DistributedOptimizer`` rather than a bare ``allreduce``.

Every in-tree compressor exposes ``wire_bytes(shape, dtype)`` — the bytes
one leaf actually costs on the wire per transfer direction — which
``grad_sync_bytes_per_step`` accounting consumes (legacy compressors
without the hook fall back to a scalar compress probe's itemsize).
"""

from __future__ import annotations

import math
import os

import jax.numpy as jnp
import numpy as np

#: elements per int8 quantization scale (one bf16 scale per block)
INT8_BLOCK = 256

#: bytes of one int8 scale on the wire (bfloat16)
_SCALE_BYTES = 2

#: smallest leaf the per-leaf int8 paths quantize. The quantized ring pads
#: every rank-pair message up to a whole scale block, so a tiny leaf (a
#: bias, a layernorm) would move MORE wire than its fp32 psum — below this
#: floor leaves pass through uncompressed and are billed dense, keeping
#: wire_bytes truthful. ~the crossover for rings up to ~32 ranks; the
#: ZeRO-1 flat-packed buffers amortize the padding and ignore this floor.
MIN_QUANT_ELEMS = 1024


def _quantizable(dtype) -> bool:
    """int8/PowerSGD compress only wide floats: f32/f64 leaves. Integer and
    already-16-bit (bf16/f16) leaves pass through uncompressed, exactly as
    fp16 compression passes integers through."""
    dt = jnp.dtype(dtype)
    return jnp.issubdtype(dt, jnp.floating) and dt.itemsize > 2


def _use_pallas(use_pallas) -> bool:
    """Resolve the per-call Pallas override against the
    ``HOROVOD_PALLAS`` knob (``None`` = knob decides)."""
    if use_pallas is not None:
        return bool(use_pallas)
    from horovod_tpu.ops import pallas_kernels as _pk

    return _pk.enabled()


def _pad_to_block(x, block: int):
    """Shared pad-to-scale-block helper: zero-pads a flat ``[L]`` vector
    (or the trailing axis of ``[n, s]`` destination-chunk rows) up to a
    multiple of ``block`` — the ONE place the wire's block alignment is
    spelled, shared by :func:`quantize_blockwise` tails,
    :func:`quantize_chunked`, the quantized collectives
    (:mod:`horovod_tpu.ops.collective`) and the serving delta encoder."""
    pad = (-x.shape[-1]) % block
    if not pad:
        return x
    if x.ndim == 1:
        return jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    return jnp.pad(x, ((0, 0),) * (x.ndim - 1) + ((0, pad),))


def quantize_blockwise(flat, block: int = INT8_BLOCK, *, use_pallas=None):
    """Blockwise-scaled int8 quantization of a flat float vector. A tail
    shorter than ``block`` is zero-padded internally (shared
    :func:`_pad_to_block` helper), so callers no longer pre-pad; ``q``
    comes back at the padded length and ``scales`` one per (padded)
    block.

    Returns ``(q, scales)``: ``q`` int8 in [-127, 127], ``scales`` bf16 —
    one max-abs/127 scale per block. The scale is rounded to bf16 *before*
    the divide so quantization and dequantization agree on the exact scale
    the wire carries (the receiver only ever sees the bf16 value).

    Under ``HOROVOD_PALLAS`` (``use_pallas=None`` consults the knob) the
    multi-op HLO sequence is replaced by the fused single-pass VMEM
    kernel :func:`horovod_tpu.ops.pallas_kernels.quantize_blockwise` —
    bit-identical output, pinned by interpret mode on CPU."""
    flat = _pad_to_block(flat, block)
    if _use_pallas(use_pallas):
        from horovod_tpu.ops import pallas_kernels as _pk

        return _pk.quantize_blockwise(flat, block)
    m = flat.reshape(-1, block)
    amax = jnp.max(jnp.abs(m), axis=1)
    scales = (amax / 127.0).astype(jnp.bfloat16)
    s = scales.astype(flat.dtype)[:, None]
    safe = jnp.where(s > 0, s, jnp.ones_like(s))
    q = jnp.where(s > 0, m / safe, jnp.zeros_like(m))
    q = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scales


def dequantize_blockwise(q, scales, dtype, block: int = INT8_BLOCK):
    """Inverse of :func:`quantize_blockwise`: int8 + bf16 scales back to a
    flat ``dtype`` vector (the f32 widening every accumulation uses)."""
    m = q.astype(dtype).reshape(-1, block)
    return (m * scales.astype(dtype)[:, None]).reshape(-1)


def dequantize_rows(qr, scr, dtype, block: int = INT8_BLOCK, *,
                    use_pallas=None):
    """Per-row dequantize of gathered int8 rows: ``qr [N, sp]`` + bf16
    scales ``scr [N, sp/block]`` → ``[N, sp]`` in ``dtype``. The ZeRO-3
    int8 parameter-gather epilogue (every row is a different rank's
    shard — NO accumulation, unlike the reduce-scatter's
    ``dequant_accumulate``). Under ``HOROVOD_PALLAS`` the multiply runs
    as one fused VMEM pass
    (:func:`horovod_tpu.ops.pallas_kernels.dequantize_rows` —
    bit-identical, pinned by interpret mode)."""
    if _use_pallas(use_pallas):
        from horovod_tpu.ops import pallas_kernels as _pk

        return _pk.dequantize_rows(qr, scr, dtype, block)
    n, sp = qr.shape
    m = qr.astype(dtype).reshape(n, sp // block, block)
    return (m * scr.astype(dtype)[:, :, None]).reshape(n, sp)


def int8_roundtrip(tensor, block: int = INT8_BLOCK):
    """What `tensor` looks like after one trip through the int8 wire
    (flat-block layout): dequant(quant(.)) — identity on non-quantizable
    dtypes and on leaves below the :data:`MIN_QUANT_ELEMS` floor (those
    ride uncompressed). vmap-safe (all shapes static), unlike the
    ``compress``/``decompress`` pair whose context carries python
    metadata."""
    if not _quantizable(getattr(tensor, "dtype", jnp.float32)) \
            or tensor.size < MIN_QUANT_ELEMS:
        return tensor
    shape, size = tensor.shape, tensor.size
    q, scales = quantize_blockwise(tensor.reshape(-1), block)
    return dequantize_blockwise(q, scales, tensor.dtype, block)[:size].reshape(
        shape)


def quantize_chunked(flat, n: int, block: int = INT8_BLOCK, *,
                     use_pallas=None):
    """The chunk-aligned wire image of a flat packed ``[Lp]`` buffer:
    ``(q, scales, rt)`` with the SAME block layout the quantized
    reduce-scatter puts on the wire — the ``[Lp]`` vector splits into
    ``n`` destination chunks, each chunk blockwise-quantized with its own
    zero-pad (shared :func:`_pad_to_block` helper, so the Pallas and HLO
    paths consume identical layouts). ``rt`` is the dequantized
    roundtrip sliced back to ``[Lp]``.

    Under Pallas the quantize and the roundtrip come out of ONE fused
    pass (:func:`horovod_tpu.ops.pallas_kernels.quantize_roundtrip`):
    error feedback's residual and the ``all_to_all`` payload share a
    single read of the corrected buffer, where the discrete path
    quantizes it twice. ``Lp`` must be a multiple of ``n``."""
    s = flat.shape[0] // n
    rows = _pad_to_block(flat.reshape(n, s), block)
    sp = rows.shape[1]
    if _use_pallas(use_pallas):
        from horovod_tpu.ops import pallas_kernels as _pk

        q, scales, deq = _pk.quantize_roundtrip(rows.reshape(-1), block)
    else:
        q, scales = quantize_blockwise(
            rows.reshape(-1), block, use_pallas=False)
        deq = dequantize_blockwise(q, scales, flat.dtype, block)
    rt = deq.reshape(n, sp)[:, :s].reshape(-1)
    return q, scales, rt


def quantize_roundtrip_chunked(flat, n: int, block: int = INT8_BLOCK):
    """Wire roundtrip of a flat packed buffer with the SAME block layout the
    quantized reduce-scatter puts on the wire (see
    :func:`quantize_chunked`). Error feedback measures its residual
    against exactly this, so the residual equals
    corrected-minus-what-the-ring-counted to the last ULP. ``Lp`` must be
    a multiple of ``n``."""
    return quantize_chunked(flat, n, block)[2]


class Compressor:
    """Interface (reference ``torch/compression.py:20-31``).

    Subclasses may additionally define ``wire_bytes(shape, dtype) -> int``
    (bytes one leaf costs per wire direction) for truthful
    ``grad_sync_bytes_per_step`` pricing; without it the accounting falls
    back to probing ``compress`` on a host scalar and billing the
    compressed itemsize per element — correct for elementwise casts only.
    """

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor

    @staticmethod
    def wire_bytes(shape, dtype) -> int:
        return int(np.prod(shape, dtype=np.int64)) * jnp.dtype(dtype).itemsize


class FP16Compressor(Compressor):
    """Casts float tensors to 16 bits for the wire (reference
    ``torch/compression.py:42-63``). bfloat16 rather than float16: TPU-native,
    no overflow scaling needed."""

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor

    @staticmethod
    def wire_bytes(shape, dtype) -> int:
        n = int(np.prod(shape, dtype=np.int64))
        dt = jnp.dtype(dtype)
        return n * (2 if jnp.issubdtype(dt, jnp.floating) else dt.itemsize)


class Int8Compressor(Compressor):
    """Blockwise-scaled int8 quantization: one bf16 max-abs scale per
    :data:`INT8_BLOCK` elements. f32/f64 leaves only; integer and 16-bit
    float leaves pass through untouched.

    ``compress``/``decompress`` are the *wire roundtrip* (what error
    feedback measures the residual against). The collectives themselves
    never sum int8: the kernels in :mod:`horovod_tpu.ops.collective`
    quantize per destination shard, move int8 + bf16 scales, widen to f32
    to accumulate, and requantize the reduced shard for the gather leg —
    the ``allreduce``/``DistributedOptimizer`` dispatch routes there
    automatically (``quantized = True``)."""

    #: marks this compressor for the quantized collective dispatch
    quantized = True
    block = INT8_BLOCK
    min_quant_elems = MIN_QUANT_ELEMS

    @classmethod
    def quantizes(cls, shape, dtype) -> bool:
        """Would a leaf of this shape/dtype ride the int8 wire? The single
        floor decision shared by ``compress``, the serving delta encoder
        (:mod:`horovod_tpu.serving.protocol`), and the analytic byte
        models — so wire accounting can never disagree with the wire."""
        n = int(np.prod(shape, dtype=np.int64))
        return _quantizable(dtype) and n >= cls.min_quant_elems

    @classmethod
    def compress(cls, tensor):
        if not _quantizable(getattr(tensor, "dtype", jnp.float32)) \
                or getattr(tensor, "size", 0) < cls.min_quant_elems:
            return tensor, None
        shape, dtype = tensor.shape, tensor.dtype
        q, scales = quantize_blockwise(tensor.reshape(-1), cls.block)
        return q, (scales, dtype, shape)

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is None:
            return tensor
        scales, dtype, shape = ctx
        flat = dequantize_blockwise(tensor, scales, dtype, cls.block)
        size = int(np.prod(shape, dtype=np.int64))
        return flat[:size].reshape(shape)

    @classmethod
    def wire_bytes(cls, shape, dtype) -> int:
        n = int(np.prod(shape, dtype=np.int64))
        if not _quantizable(dtype) or n < cls.min_quant_elems:
            return n * jnp.dtype(dtype).itemsize
        return n + math.ceil(n / cls.block) * _SCALE_BYTES


class PowerSGDCompressor(Compressor):
    """Rank-``r`` low-rank gradient factorization (PowerSGD, Vogels et al.
    2019): a >=2-D leaf ``M`` (reshaped ``[d0, prod(rest)]``) syncs only
    ``P = M @ Q`` and ``Q_new = M^T @ P`` — ``(d0 + m) * r`` floats instead
    of ``d0 * m`` — with one Gram-Schmidt orthogonalization of the
    aggregated ``P`` per step and ``Q`` warm-started across steps.

    Stateful: the warm-started ``Q`` and the error-feedback residual live
    in the optimizer state, so this compressor only rides
    ``DistributedOptimizer(compression=Compression.powersgd(r),
    error_feedback=True)`` (a bare ``allreduce`` rejects it). 1-D (and
    integer/16-bit) leaves fall back to the int8 path. ``compress`` /
    ``decompress`` here are the stateless int8 fallback so legacy probes
    and the 1-D roundtrip work; the factorization itself is performed by
    :mod:`horovod_tpu.optim`."""

    #: marks this compressor as factorized/stateful for the optim dispatch
    factorized = True
    quantized = True  # the non-factorized leaves ride the int8 wire
    block = INT8_BLOCK
    #: the stateless compressor non-factorized leaves ride
    fallback = Int8Compressor

    def __init__(self, rank: int = 4):
        if rank < 1:
            raise ValueError(f"PowerSGD rank must be >= 1, got {rank}")
        self.rank = int(rank)

    def effective_rank(self, shape) -> int:
        d0 = int(shape[0])
        m = int(np.prod(shape[1:], dtype=np.int64))
        return min(self.rank, d0, m)

    def factorizes(self, shape, dtype) -> bool:
        """Factorize only when the P/Q factors actually cost less wire
        than the dense leaf: ``(d0 + m) * r < d0 * m``. A tiny matrix
        would otherwise pay TWO ring allreduces plus truncation error to
        move MORE bytes; it falls back to the int8/dense path instead."""
        if len(shape) < 2 or not _quantizable(dtype):
            return False
        r = self.effective_rank(shape)
        d0 = int(shape[0])
        m = int(np.prod(shape[1:], dtype=np.int64))
        return r >= 1 and (d0 + m) * r < d0 * m

    def compress(self, tensor):
        return Int8Compressor.compress(tensor)

    def decompress(self, tensor, ctx):
        return Int8Compressor.decompress(tensor, ctx)

    def wire_bytes(self, shape, dtype) -> int:
        if not self.factorizes(shape, dtype):
            return Int8Compressor.wire_bytes(shape, dtype)
        d0 = int(shape[0])
        m = int(np.prod(shape[1:], dtype=np.int64))
        r = self.effective_rank(shape)
        # P [d0, r] + Q [m, r], f32 factors on the wire
        return (d0 + m) * r * 4

    def __repr__(self):  # shows up in bench JSON / error messages
        return f"PowerSGD(rank={self.rank})"


class Compression:
    """Namespace mirroring ``hvd.Compression`` (reference
    ``torch/compression.py:66-73``), extended with the low-bit compressors."""

    none = NoneCompressor
    fp16 = FP16Compressor
    int8 = Int8Compressor

    @staticmethod
    def powersgd(rank: int = None) -> PowerSGDCompressor:
        """Rank-``r`` PowerSGD compressor (default: env
        ``HOROVOD_POWERSGD_RANK``, else 4)."""
        if rank is None:
            rank = int(os.environ.get("HOROVOD_POWERSGD_RANK", "4"))
        return PowerSGDCompressor(rank)

    @staticmethod
    def from_env(default=NoneCompressor):
        """Resolve ``HOROVOD_COMPRESSION`` (``none``/``fp16``/``int8``/
        ``powersgd``) — the env spelling of the ``compression=`` kwarg;
        ``DistributedOptimizer`` consults this when no compressor is passed
        explicitly."""
        name = os.environ.get("HOROVOD_COMPRESSION", "").strip().lower()
        if not name:
            return default
        if name in ("none", "off", "0"):
            return NoneCompressor
        if name in ("fp16", "bf16", "16bit"):
            return FP16Compressor
        if name == "int8":
            return Int8Compressor
        if name == "powersgd":
            return Compression.powersgd()
        raise ValueError(
            f"HOROVOD_COMPRESSION={name!r}: expected one of "
            "none|fp16|int8|powersgd"
        )
