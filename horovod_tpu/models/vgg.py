"""VGG family (flax) — the reference's third scaling-benchmark workload.

The reference's scaling table benchmarks VGG-16 alongside ResNet-101 and
Inception V3 (``docs/benchmarks.rst:10-14``: 68% efficiency at 512 GPUs —
VGG's two 4096-wide FC layers dominate gradient volume, which is exactly what
made it the stress case for allreduce bandwidth). From-scratch flax
implementation of the classic configuration (Simonyan & Zisserman 2014),
TPU-tuned like the ResNet family: bfloat16 compute / float32 params, NHWC.

Batch norm is off by default (the classic benchmark network has none, so the
whole model is stateless — ``batch_stats`` comes back empty); ``use_bn=True``
gives the modern variant. No dropout: the synthetic-benchmark harness never
regularizes, and the shipped train-step helpers pass no rngs at apply time.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Stage layout: conv filter counts between max-pools.
_VGG16_STAGES = ((64, 64), (128, 128), (256, 256, 256),
                 (512, 512, 512), (512, 512, 512))
_VGG19_STAGES = ((64, 64), (128, 128), (256, 256, 256, 256),
                 (512, 512, 512, 512), (512, 512, 512, 512))


class VGG(nn.Module):
    stages: Sequence[Sequence[int]]
    num_classes: int = 1000
    hidden_dim: int = 4096
    dtype: Any = jnp.bfloat16
    use_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, kernel_size=(3, 3), padding="SAME",
            use_bias=not self.use_bn, dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        for i, stage in enumerate(self.stages):
            for j, filters in enumerate(stage):
                x = conv(filters, name=f"conv{i}_{j}")(x)
                if self.use_bn:
                    x = nn.BatchNorm(
                        use_running_average=not train, momentum=0.9,
                        epsilon=1e-5, dtype=self.dtype, name=f"bn{i}_{j}",
                    )(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for k in range(2):
            x = nn.Dense(self.hidden_dim, dtype=self.dtype, name=f"fc{k}")(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


VGG16 = functools.partial(VGG, stages=_VGG16_STAGES)
VGG19 = functools.partial(VGG, stages=_VGG19_STAGES)
