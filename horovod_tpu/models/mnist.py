"""Small MNIST CNN, the "baseline config 1" model
(reference ``examples/tensorflow2_mnist.py``: conv32-conv64-pool-dense128)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x)
        return x
