"""Decoder-only Transformer LM (flax) — the long-context benchmark workload.

No counterpart in the reference (its models are CNN benchmark harnesses,
``examples/tensorflow2_synthetic_benchmark.py``); this family exists to
exercise the TPU-native parallel axes the mesh layer provides beyond data
parallelism: sequence (ring/Ulysses attention over ``seq``), tensor (MLP and
attention projections sharded over ``model``), on top of DP.

TPU-tuned defaults: bfloat16 compute with float32 params, pre-LN blocks,
dimensions sized for MXU tiling (head_dim and mlp widths multiples of 128 at
benchmark scale). The attention implementation is injectable so the same
module runs dense attention under plain jit, flash attention single-chip, or
ring attention inside a ``shard_map`` over the ``seq`` axis.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def apply_rope(x, positions, *, base: float = 10000.0):
    """Rotary position embedding on ``[B, T, H, D]`` (D even), rotate-half
    (NeoX-style) convention: feature i pairs with feature i + D/2, rotated
    by ``positions * base**(-2i/D)``.

    Positions are the *global* token indices, so under sequence parallelism
    each shard rotates with its own offsets and ring/Ulysses attention sees
    correctly phased K — relative-position behavior is preserved across
    shard boundaries (the property that makes RoPE the long-context default
    over a learned absolute table)."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B?, T, half]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(
        jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def default_attention(q, k, v, *, causal: bool = True, sm_scale=None):
    """Dense attention fallback (plain jit / tiny shapes). GQA-aware like
    the flash/ring implementations: K/V may carry fewer heads than Q."""
    if k.shape[2] != q.shape[2]:
        from horovod_tpu.ops.flash_attention import repeat_kv_heads

        k, v = repeat_kv_heads(q, k, v)
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _decode_attention(q, k_cache, v_cache, start_pos):
    """Moved to :func:`horovod_tpu.ops.flash_attention.decode_attention`
    (the serving engine's paged variant shares the primitive); this alias
    keeps the historical name importable."""
    from horovod_tpu.ops.flash_attention import decode_attention

    return decode_attention(q, k_cache, v_cache, start_pos)


class TransformerBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int
    dtype: Any
    attention_fn: Callable
    kv_heads: Optional[int] = None  # GQA: fewer K/V heads (MQA = 1)
    use_rope: bool = False
    rope_base: float = 10000.0
    decode: bool = False
    cache_len: int = 0  # kv-cache capacity when decode=True
    # paged decode (the serving engine): the cache is a shared page pool
    # [num_pages, page_size, H_kv, D] addressed through a per-row page
    # table instead of one contiguous [B, cache_len, ...] buffer
    paged: bool = False
    page_size: int = 0
    num_pages: int = 0

    @nn.compact
    def __call__(self, x, positions=None, page_table=None):
        head_dim = self.dim // self.heads
        h_kv = self.kv_heads or self.heads
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        if h_kv == self.heads:
            qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype,
                           name="qkv")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            # GQA: smaller K/V projections — parameter AND kv-cache savings
            # flow straight through to the attention stack (the ring/zigzag
            # ppermute bundles and the Pallas kv buffers stay H_kv-wide)
            q = nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                         name="q_proj")(h)
            kv = nn.Dense(2 * h_kv * head_dim, use_bias=False,
                          dtype=self.dtype, name="kv_proj")(h)
            k, v = jnp.split(kv, 2, axis=-1)
        split_q = lambda t: t.reshape(*t.shape[:2], self.heads, head_dim)
        split_kv = lambda t: t.reshape(*t.shape[:2], h_kv, head_dim)
        q, k, v = split_q(q), split_kv(k), split_kv(v)
        if self.use_rope:
            if positions is None:
                # a silent local-arange fallback would be wrong under SP
                # (every shard would phase from 0); demand global offsets
                raise ValueError(
                    "use_rope=True requires positions (global token "
                    "indices) — TransformerLM passes them automatically"
                )
            q = apply_rope(q, positions, base=self.rope_base)
            k = apply_rope(k, positions, base=self.rope_base)
        if self.decode and self.paged:
            from horovod_tpu.ops.flash_attention import (
                paged_decode_attention,
            )

            if page_table is None:
                raise ValueError(
                    "paged decode requires a page_table ([B, pages_per_"
                    "seq] int32) — the serving engine passes it")
            # page pool [P, page_size, H_kv, D]: token at global position
            # p of row b lives in page page_table[b, p // page_size] at
            # offset p % page_size. Writes scatter the chunk's T tokens
            # into their flat pool slots; the engine routes masked rows /
            # pad tail positions to a reserved trash page (page 0), whose
            # contents are never causally visible.
            cache_k = self.variable(
                "cache", "k_pages", jnp.zeros,
                (self.num_pages, self.page_size, h_kv, head_dim),
                self.dtype)
            cache_v = self.variable(
                "cache", "v_pages", jnp.zeros,
                (self.num_pages, self.page_size, h_kv, head_dim),
                self.dtype)
            page_idx = positions // self.page_size          # [B, T]
            offset = positions % self.page_size
            # out-of-range page_idx clamps under jit (take_along_axis),
            # matching the engine's contract that over-capacity positions
            # only ever carry masked pad tokens
            page_ids = jnp.take_along_axis(
                page_table, jnp.minimum(
                    page_idx, page_table.shape[1] - 1), axis=1)
            slots = (page_ids * self.page_size + offset).reshape(-1)
            flat_shape = (self.num_pages * self.page_size, h_kv, head_dim)
            kf = cache_k.value.reshape(flat_shape).at[slots].set(
                k.astype(self.dtype).reshape(-1, h_kv, head_dim))
            vf = cache_v.value.reshape(flat_shape).at[slots].set(
                v.astype(self.dtype).reshape(-1, h_kv, head_dim))
            cache_k.value = kf.reshape(cache_k.value.shape)
            cache_v.value = vf.reshape(cache_v.value.shape)
            start = positions[:, 0]  # [B], per-row frontier
            att = paged_decode_attention(
                q, cache_k.value, cache_v.value, page_table, start,
                page_size=self.page_size)
        elif self.decode:
            # chunk of T tokens in, kv cache [B, cache_len, H_kv, D] updated
            # in place at each row's start position (GQA: H_kv-wide — the
            # cache memory saving). T = prompt length on prefill, 1 after.
            b = x.shape[0]
            cache_k = self.variable(
                "cache", "k", jnp.zeros,
                (b, self.cache_len, h_kv, head_dim), self.dtype)
            cache_v = self.variable(
                "cache", "v", jnp.zeros,
                (b, self.cache_len, h_kv, head_dim), self.dtype)
            start = positions[:, 0]  # [B], per-row write offset
            upd = jax.vmap(
                lambda c, kv, p: jax.lax.dynamic_update_slice(
                    c, kv, (p, 0, 0))
            )
            cache_k.value = upd(cache_k.value, k.astype(self.dtype), start)
            cache_v.value = upd(cache_v.value, v.astype(self.dtype), start)
            att = _decode_attention(q, cache_k.value, cache_v.value, start)
        else:
            att = self.attention_fn(q, k, v, causal=True)
        att = att.reshape(*att.shape[:2], self.dim)
        x = x + nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                         name="proj")(att)

        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = nn.Dense(self.mlp_ratio * self.dim, dtype=self.dtype,
                     name="mlp_up")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM. Input: int tokens [B, T] (a *local* sequence shard when run
    under sequence parallelism — pass ``positions`` with the global offsets so
    position embeddings line up). Output: logits [B, T, vocab]."""

    vocab: int = 32000
    dim: int = 512
    depth: int = 8
    heads: int = 8
    kv_heads: Optional[int] = None  # GQA (heads % kv_heads == 0); MQA = 1
    mlp_ratio: int = 4
    max_len: int = 65536
    dtype: Any = jnp.bfloat16
    attention_fn: Callable = default_attention
    pos_embedding: str = "learned"  # "learned" table or "rope" (rotary)
    rope_base: float = 10000.0
    decode: bool = False  # chunked/single-token steps against a kv cache
    cache_len: Optional[int] = None  # kv-cache capacity (default: max_len)
    paged: bool = False  # page-pool kv cache (serving engine)
    page_size: int = 0
    num_pages: int = 0

    @nn.compact
    def __call__(self, tokens, positions=None, train: bool = True,
                 page_table=None):
        if self.pos_embedding not in ("learned", "rope"):
            raise ValueError(
                f"pos_embedding must be 'learned' or 'rope', "
                f"got {self.pos_embedding!r}"
            )
        if self.pos_embedding == "rope" and (self.dim // self.heads) % 2:
            raise ValueError(
                f"rope needs an even head_dim, got "
                f"{self.dim // self.heads} (dim={self.dim}, "
                f"heads={self.heads})"
            )
        if self.decode and positions is None:
            raise ValueError(
                "decode=True requires positions (the current cache index "
                "as a [B, 1] array) — use generate()"
            )
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :]
        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype,
                     name="tok_embed")(tokens)
        use_rope = self.pos_embedding == "rope"
        if not use_rope:
            pos_table = self.param(
                "pos_embed",
                nn.initializers.normal(0.02),
                (self.max_len, self.dim),
            )
            # jnp.take clamps out-of-range indices under jit: a paged
            # prefill chunk's masked pad tail may carry positions past the
            # table — those rows' logits are never consumed
            x = x + jnp.take(pos_table, positions, axis=0).astype(self.dtype)
        for i in range(self.depth):
            x = TransformerBlock(
                self.dim, self.heads, self.mlp_ratio, self.dtype,
                self.attention_fn, kv_heads=self.kv_heads,
                use_rope=use_rope, rope_base=self.rope_base,
                decode=self.decode,
                cache_len=self.cache_len or self.max_len,
                paged=self.paged, page_size=self.page_size,
                num_pages=self.num_pages,
                name=f"block{i}",
            )(x, positions=positions if (use_rope or self.decode) else None,
              page_table=page_table)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = nn.Dense(self.vocab, use_bias=False, dtype=self.dtype,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)


def TransformerTiny(**kw):
    kw.setdefault("vocab", 1024)
    kw.setdefault("dim", 64)
    kw.setdefault("depth", 2)
    kw.setdefault("heads", 4)
    kw.setdefault("max_len", 4096)
    return TransformerLM(**kw)


def TransformerSmall(**kw):
    """~GPT-2-small scale; dims are MXU-tile multiples."""
    kw.setdefault("vocab", 32768)
    kw.setdefault("dim", 768)
    kw.setdefault("depth", 12)
    kw.setdefault("heads", 12)
    return TransformerLM(**kw)


def transformer_param_specs(params, model_axis: str = "model"):
    """Tensor-parallel PartitionSpecs for a TransformerLM param tree
    (Megatron-style: qkv/up-proj sharded on the output dim, proj/down-proj on
    the input dim, so each block needs exactly one psum — which XLA inserts
    from these annotations; embeddings/vocab sharded on the feature axis)."""
    from jax.sharding import PartitionSpec as P

    def spec_for(path, leaf):
        names = [getattr(p, "key", str(p)) for p in path]
        name = "/".join(names)
        if leaf.ndim < 2:
            return P()
        if ("qkv" in name or "mlp_up" in name or "q_proj" in name
                or "kv_proj" in name):
            return P(None, model_axis)
        if "proj" in name or "mlp_down" in name:
            return P(model_axis, None)
        if "lm_head" in name:
            return P(None, model_axis)
        if "tok_embed" in name or "pos_embed" in name:
            return P(None, model_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def _tp_layernorm(x, scale, bias, *, eps: float = 1e-6):
    # flax.linen.LayerNorm's stats formula (mean-of-squares minus squared
    # mean, clamped) so tp_block_apply is numerically interchangeable with
    # TransformerBlock.apply
    mu = jnp.mean(x, axis=-1, keepdims=True)
    mu2 = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    var = jnp.maximum(0.0, mu2 - jnp.square(mu))
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def tp_block_apply(block_params, x, *, heads: int, axis: str = "tp"):
    """One transformer block, tensor-parallel over a bound mesh axis.

    The explicit (shard_map) counterpart of the GSPMD annotations from
    :func:`transformer_param_specs` — which remains the production TP
    path; this function exists so the one-psum-per-matmul-pair schedule
    is stated in code rather than inferred by the partitioner, and so
    tests can pin the two against each other. Call it *inside* a
    shard_map region over ``axis`` with the full (replicated) param dict
    of a single :class:`TransformerBlock`; each rank slices its own
    column/row blocks (Megatron-style: qkv and mlp_up column-split,
    proj and mlp_down row-split) so the block costs exactly two psums —
    one after the attention projection, one after mlp_down.

    Restrictions: full multi-head attention only (``kv_heads`` unset or
    equal to ``heads`` — the params must carry a fused ``qkv`` kernel),
    no RoPE, no kv-cache (training/prefill layout, ``decode=False``).
    ``heads`` and the mlp hidden width must be divisible by the axis
    size.
    """
    from horovod_tpu.ops.collective import _axis_size

    if "qkv" not in block_params:
        raise ValueError(
            "tp_block_apply requires a fused qkv kernel (kv_heads unset "
            "or == heads); GQA blocks need the GSPMD path "
            "(transformer_param_specs)")
    n = _axis_size(axis)
    r = jax.lax.axis_index(axis)
    dim = x.shape[-1]
    if heads % n:
        raise ValueError(f"heads={heads} not divisible by tp axis size {n}")
    w = dim // n  # per-rank head-block width (heads//n heads, contiguous)
    head_dim = dim // heads

    def cols(kernel, off, width):
        return jax.lax.dynamic_slice_in_dim(kernel, off, width, axis=1)

    def rows(kernel, off, width):
        return jax.lax.dynamic_slice_in_dim(kernel, off, width, axis=0)

    h = _tp_layernorm(x, block_params["ln1"]["scale"],
                      block_params["ln1"]["bias"])
    # fused qkv kernel layout is [D, 3D] = [q | k | v]; this rank takes
    # the same column window r*w inside each third
    qkv_k = block_params["qkv"]["kernel"]
    qkv_local = jnp.concatenate(
        [cols(qkv_k, base + r * w, w) for base in (0, dim, 2 * dim)],
        axis=1)
    qkv = h @ qkv_local                                     # [B, T, 3w]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda t: t.reshape(*t.shape[:2], heads // n, head_dim)
    att = default_attention(split(q), split(k), split(v), causal=True)
    att = att.reshape(*att.shape[:2], w)
    # proj row-split: each rank contributes its head-block's slice of the
    # contraction; psum #1 completes it
    partial = att @ rows(block_params["proj"]["kernel"], r * w, w)
    x = x + jax.lax.psum(partial, axis)

    h = _tp_layernorm(x, block_params["ln2"]["scale"],
                      block_params["ln2"]["bias"])
    up_k = block_params["mlp_up"]["kernel"]
    hidden = up_k.shape[1]
    if hidden % n:
        raise ValueError(
            f"mlp hidden width {hidden} not divisible by tp axis size {n}")
    fw = hidden // n
    # mlp_up bias is column-split with its kernel: it must land before the
    # gelu nonlinearity, so it cannot wait for the psum
    h = h @ cols(up_k, r * fw, fw) + jax.lax.dynamic_slice_in_dim(
        block_params["mlp_up"]["bias"], r * fw, fw, axis=0)
    h = nn.gelu(h)
    partial = h @ rows(block_params["mlp_down"]["kernel"], r * fw, fw)
    # mlp_down bias is replicated and must be added exactly once — after
    # psum #2, not inside the summed partials
    return x + jax.lax.psum(partial, axis) + block_params["mlp_down"]["bias"]


def generate(model: TransformerLM, params, prompt, *, max_new_tokens: int,
             temperature: float = 0.0, rng=None, prompt_lens=None):
    """Autoregressive decoding with a KV cache (the inference path;
    reference ``docs/inference.rst`` covers only checkpoint handling — the
    reference has no model code to decode with).

    One batched prefill forward writes the whole prompt's K/V into the
    cache, then a ``lax.scan`` decodes one token per step — greedy
    (``temperature=0``) or categorical sampling. The cache is sized to
    ``T_prompt + max_new_tokens`` (not ``max_len``) and holds ``H_kv``-wide
    K/V per block (GQA's memory saving) — static shapes throughout, the
    standard TPU decode loop.

    Ragged batches: pass ``prompt_lens`` ``[B]`` with RIGHT-padded
    ``prompt`` (pad values are arbitrary) and every row decodes from its
    own length — per-row cache offsets/causal masks make the pad slots
    unreachable until a real decode step overwrites them, so no attention
    masking of pads is needed.

    Args:
      model: a ``TransformerLM`` (its ``decode``/``cache_len`` are
        overridden).
      params: trained parameter tree.
      prompt: int tokens ``[B, T_prompt]`` (right-padded when ragged).
      max_new_tokens: tokens to append (per row).
      temperature: 0 = greedy argmax; > 0 = sample logits/temperature.
      rng: PRNGKey, required when ``temperature > 0``.
      prompt_lens: optional ``[B]`` true prompt lengths (1..T_prompt).

    Returns:
      int tokens ``[B, T_prompt + max_new_tokens]``; ragged rows carry
      their generated tokens at ``[L_i, L_i + max_new_tokens)`` — columns
      beyond that are unspecified padding.
    """
    import dataclasses

    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    b, t_prompt = prompt.shape
    total = t_prompt + max_new_tokens
    if total > model.max_len:
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds max_len "
            f"{model.max_len}"
        )
    prompt = jnp.asarray(prompt, jnp.int32)
    ragged = prompt_lens is not None
    if ragged:
        lens = jnp.asarray(prompt_lens, jnp.int32)
        if lens.shape != (b,):
            raise ValueError(f"prompt_lens must be [B]={b}, got {lens.shape}")
        if not isinstance(lens, jax.core.Tracer):
            lo, hi = int(lens.min()), int(lens.max())
            if lo < 1 or hi > t_prompt:
                raise ValueError(
                    f"prompt_lens must be in [1, {t_prompt}], got "
                    f"[{lo}, {hi}]"
                )
    else:
        lens = jnp.full((b,), t_prompt, jnp.int32)
    dec = dataclasses.replace(model, decode=True, cache_len=total, name=None)
    base_rng = rng if rng is not None else jax.random.PRNGKey(0)

    def sample(logits, i):
        if temperature > 0.0:
            return jax.random.categorical(
                jax.random.fold_in(base_rng, i),
                logits / temperature, axis=-1,
            ).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # zero cache from shapes only — no throwaway parameter init
    prefill_pos = jnp.broadcast_to(
        jnp.arange(t_prompt, dtype=jnp.int32)[None, :], (b, t_prompt))
    shapes = jax.eval_shape(
        dec.init, jax.random.PRNGKey(0), prompt, positions=prefill_pos
    )["cache"]
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    # prefill: one forward over the (padded) prompt fills the cache; pad
    # K/V beyond a row's length stays masked until decode overwrites it
    logits, mut = dec.apply(
        {"params": params, "cache": cache}, prompt,
        positions=prefill_pos, mutable=["cache"],
    )
    # each row's first sampled token comes from ITS last real position
    last_logits = jnp.take_along_axis(
        logits, (lens - 1)[:, None, None], axis=1)[:, 0]
    # rng fold indices: prefill samples at 0, decode step i at i+1 —
    # disjoint by construction, so no two draws share a folded key
    first = sample(last_logits, 0)

    def step(carry, i):
        cache, tok = carry
        pos = (lens + i)[:, None]  # [B, 1], per-row decode position
        logits, mut = dec.apply(
            {"params": params, "cache": cache}, tok[:, None],
            positions=pos, mutable=["cache"],
        )
        nxt = sample(logits[:, -1], i + 1)
        return (mut["cache"], nxt), nxt

    (_, _), ys = jax.lax.scan(
        step, (mut["cache"], first),
        jnp.arange(max_new_tokens - 1, dtype=jnp.int32),
    )
    gen = jnp.concatenate([first[:, None], ys.T], axis=1)

    out = jnp.pad(prompt, ((0, 0), (0, max_new_tokens)))
    # place each row's generated run at its own offset
    return jax.vmap(
        lambda row, g, l: jax.lax.dynamic_update_slice(row, g, (l,))
    )(out, gen, lens)
