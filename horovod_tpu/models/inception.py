"""Inception V3 (flax) — the reference's second scaling-benchmark workload.

The reference's scaling table benchmarks Inception V3 alongside ResNet-101
and VGG-16 (``docs/benchmarks.rst:10-14``: 90% efficiency at 512 GPUs).
From-scratch flax implementation of the factorized-convolution architecture
(Szegedy et al. 2015, "Rethinking the Inception Architecture"), TPU-tuned
like the rest of the family: bfloat16 compute / float32 params, NHWC,
BatchNorm running stats in ``batch_stats``.

Canonical input is 299x299; the network is fully convolutional up to the
global average-pool, so any spatial size that survives the stem's three
stride-2 stages works (tests use 128x128). The auxiliary classifier head is
omitted: it exists for training-era gradient flow on 2015 optimizers, adds a
second loss term the benchmark harness never uses, and costs MXU time.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    """conv + BN + relu, the unit every Inception branch is built from."""

    filters: int
    kernel: Sequence[int]
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(
            self.filters, tuple(self.kernel), tuple(self.strides),
            padding=self.padding, use_bias=False, dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-3,
            dtype=self.dtype,
        )(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool branches."""

    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype)
        b1 = conv(64, (1, 1))(x, train)
        b2 = conv(48, (1, 1))(x, train)
        b2 = conv(64, (5, 5))(b2, train)
        b3 = conv(64, (1, 1))(x, train)
        b3 = conv(96, (3, 3))(b3, train)
        b3 = conv(96, (3, 3))(b3, train)
        b4 = conv(self.pool_features, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    """35x35 -> 17x17 grid reduction."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype)
        b1 = conv(384, (3, 3), (2, 2), padding="VALID")(x, train)
        b2 = conv(64, (1, 1))(x, train)
        b2 = conv(96, (3, 3))(b2, train)
        b2 = conv(96, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """17x17 block: 7x7 factorized into 1x7/7x1 pairs."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype)
        c = self.channels_7x7
        b1 = conv(192, (1, 1))(x, train)
        b2 = conv(c, (1, 1))(x, train)
        b2 = conv(c, (1, 7))(b2, train)
        b2 = conv(192, (7, 1))(b2, train)
        b3 = conv(c, (1, 1))(x, train)
        b3 = conv(c, (7, 1))(b3, train)
        b3 = conv(c, (1, 7))(b3, train)
        b3 = conv(c, (7, 1))(b3, train)
        b3 = conv(192, (1, 7))(b3, train)
        b4 = conv(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    """17x17 -> 8x8 grid reduction."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype)
        b1 = conv(192, (1, 1))(x, train)
        b1 = conv(320, (3, 3), (2, 2), padding="VALID")(b1, train)
        b2 = conv(192, (1, 1))(x, train)
        b2 = conv(192, (1, 7))(b2, train)
        b2 = conv(192, (7, 1))(b2, train)
        b2 = conv(192, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    """8x8 block: 3x3 branches fan out into parallel 1x3 and 3x1."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype)
        b1 = conv(320, (1, 1))(x, train)
        b2 = conv(384, (1, 1))(x, train)
        b2 = jnp.concatenate(
            [conv(384, (1, 3))(b2, train), conv(384, (3, 1))(b2, train)],
            axis=-1,
        )
        b3 = conv(448, (1, 1))(x, train)
        b3 = conv(384, (3, 3))(b3, train)
        b3 = jnp.concatenate(
            [conv(384, (1, 3))(b3, train), conv(384, (3, 1))(b3, train)],
            axis=-1,
        )
        b4 = conv(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem: 299 -> 35 spatial
        x = conv(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = conv(32, (3, 3), padding="VALID")(x, train)
        x = conv(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = conv(80, (1, 1), padding="VALID")(x, train)
        x = conv(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        x = InceptionA(32, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionB(dtype=self.dtype)(x, train)
        x = InceptionC(128, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(192, dtype=self.dtype)(x, train)
        x = InceptionD(dtype=self.dtype)(x, train)
        x = InceptionE(dtype=self.dtype)(x, train)
        x = InceptionE(dtype=self.dtype)(x, train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)
