"""ResNet family (flax), the benchmark workload.

The reference's headline numbers are ResNet-50/101 synthetic-image throughput
(``examples/tensorflow2_synthetic_benchmark.py:12-100`` with
``tf.keras.applications.ResNet50``; ``docs/benchmarks.rst:26-42``). This is a
from-scratch flax implementation of the standard v1.5 architecture
(stride-2 on the 3x3 conv in bottlenecks), TPU-tuned defaults:

- compute dtype bfloat16, parameters float32 (MXU-native mixed precision);
- NHWC layout (XLA:TPU's preferred conv layout);
- BatchNorm with running stats in a mutable ``batch_stats`` collection.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic two-conv residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1-3-1 bottleneck block (ResNet-50/101/152), v1.5: stride on the 3x3."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = functools.partial(
    ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock
)
ResNet152 = functools.partial(
    ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock
)
