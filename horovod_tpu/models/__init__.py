"""Model zoo for benchmarks and examples.

The reference ships models only as examples/benchmark harnesses
(``examples/tensorflow2_synthetic_benchmark.py`` uses Keras ResNet-50,
``examples/tensorflow2_mnist.py`` a small CNN); these are their TPU-native
(flax) equivalents, used by ``bench.py`` and the test suite.
"""

from horovod_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from horovod_tpu.models.vgg import VGG, VGG16, VGG19  # noqa: F401
from horovod_tpu.models.inception import InceptionV3  # noqa: F401
from horovod_tpu.models.mnist import MnistCNN  # noqa: F401
from horovod_tpu.models.mlp import MLP  # noqa: F401
from horovod_tpu.models.transformer import (  # noqa: F401
    TransformerLM,
    TransformerTiny,
    TransformerSmall,
    generate,
    transformer_param_specs,
)
