"""Small MLP for tests and the Adasum toy example
(reference ``examples/adasum_small_model.py`` uses a tiny dense model)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn


class MLP(nn.Module):
    features: Sequence[int] = (64, 10)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        for i, f in enumerate(self.features):
            x = nn.Dense(f)(x)
            if i + 1 < len(self.features):
                x = nn.relu(x)
        return x
