#!/usr/bin/env python
"""On-chip validation of the Pallas flash-attention kernel.

Runs the Pallas kernel and the mathematically-identical ``lax.scan`` path on
the same inputs on the default backend (intended: real TPU), checks
equivalence, and times both. Emits ONE JSON line so the TPU-window watcher
can capture it as an artifact (VERDICT r3 item 5: this kernel had never
executed on its target platform).

Usage: python tools/flash_onchip_check.py [--batch 4 --heads 16 --seq 2048 --dim 64]
"""

import argparse
import json
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--causal", action=argparse.BooleanOptionalAction,
                   default=True)
    args = p.parse_args()

    sys.path.insert(0, ".")
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()  # watchdog SIGTERM -> clean device teardown

    import jax
    import jax.numpy as jnp

    from horovod_tpu.ops.flash_attention import flash_attention

    dev = jax.devices()[0]
    platform = dev.platform
    kind = getattr(dev, "device_kind", "?")

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    # flash_attention expects [B, T, H, D]
    shape = (args.batch, args.seq, args.heads, args.dim)
    q = jax.random.normal(kq, shape, dtype=jnp.bfloat16)
    k = jax.random.normal(kk, shape, dtype=jnp.bfloat16)
    v = jax.random.normal(kv, shape, dtype=jnp.bfloat16)

    def bench(fn):
        out = fn(q, k, v)  # compile + correctness sample
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(q, k, v)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / args.iters
        return out, dt

    scan_fn = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=args.causal, use_pallas=False)
    )
    pallas_fn = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=args.causal, use_pallas=True)
    )

    out_scan, t_scan = bench(scan_fn)
    try:
        out_pallas, t_pallas = bench(pallas_fn)
    except Exception as e:  # kernel failed on this backend — that IS the finding
        print(
            json.dumps(
                {
                    "metric": "flash_attention_pallas_onchip",
                    "value": None,
                    "unit": "ms",
                    "platform": platform,
                    "device_kind": kind,
                    "error": f"{type(e).__name__}: {e}"[:500],
                }
            ),
            flush=True,
        )
        return 1

    diff = float(
        jnp.max(jnp.abs(out_pallas.astype(jnp.float32) - out_scan.astype(jnp.float32)))
    )
    # tokens/s across batch*seq for the pallas path
    toks = args.batch * args.seq
    print(
        json.dumps(
            {
                "metric": "flash_attention_pallas_onchip",
                "value": round(t_pallas * 1e3, 3),
                "unit": "ms",
                "platform": platform,
                "device_kind": kind,
                "scan_ms": round(t_scan * 1e3, 3),
                "speedup_vs_scan": round(t_scan / t_pallas, 3) if t_pallas else None,
                "max_abs_diff": diff,
                "equivalent": diff < 0.06,  # bf16 accumulation tolerance
                "tokens_per_sec": round(toks / t_pallas, 1),
                "shape": list(shape),
            }
        ),
        flush=True,
    )
    return 0 if diff < 0.06 else 2


if __name__ == "__main__":
    sys.exit(main())
