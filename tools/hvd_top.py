#!/usr/bin/env python3
"""hvd_top — live fleet view of a horovod_tpu job's metrics endpoint.

The fleet-observability analog of ``top``: poll the rank-0 HTTP endpoint
(``HOROVOD_METRICS_PORT``) and render a refreshing terminal table of the
cross-rank picture — per-metric min/mean/max/p99 with the per-rank values,
dead ranks called out, and the current straggler attribution on its own
line. Falls back to the single-process ``/metrics.json`` view when no fleet
aggregator is registered (then every stat column is just the one process's
value).

Usage::

    HOROVOD_METRICS_PORT=9090 python train.py &
    python tools/hvd_top.py --url http://127.0.0.1:9090
    python tools/hvd_top.py --once --json          # one scrape, raw JSON
    python tools/hvd_top.py --filter straggler     # substring metric filter

stdlib-only (urllib + ANSI clear), like everything else in the
observability stack — pointing a dashboard at a training job must never
require a new dependency.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def fetch(url: str, timeout: float = 5.0):
    """(payload, fleet: bool) — tries ``/fleet.json`` first, falls back to
    ``/metrics.json`` shaped into the fleet structure (one rank, rank 0)."""
    try:
        with urllib.request.urlopen(f"{url}/fleet.json", timeout=timeout) as r:
            return json.load(r), True
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise
    with urllib.request.urlopen(f"{url}/metrics.json", timeout=timeout) as r:
        snap = json.load(r)
    fleet = {
        "collected_at": time.time(),
        "ranks": [0],
        "dead_ranks": [],
        "metrics": _single_rank_fleet(snap),
        "straggler": None,
    }
    return fleet, False


def _single_rank_fleet(snap: dict) -> dict:
    out = {}
    for name, fam in snap.items():
        samples = {}
        for key, sample in fam.get("samples", {}).items():
            if fam["type"] == "histogram":
                samples[key] = dict(sample, p99=None)
            else:
                v = float(sample)
                samples[key] = {
                    "ranks": {"0": v},
                    "min": v, "mean": v, "max": v, "p99": v,
                }
        out[name] = {"type": fam["type"], "help": fam.get("help", ""),
                     "samples": samples}
    return out


def _gauge_stat(metrics: dict, name: str, stat: str = "max"):
    """One summary stat of a scalar fleet family (None when absent)."""
    fam = metrics.get(name)
    if not fam:
        return None
    samples = fam.get("samples", {})
    if not samples:
        return None
    vals = [s.get(stat) for s in samples.values() if s.get(stat) is not None]
    return max(vals) if vals else None


def _label_sums(metrics: dict, name: str) -> dict:
    """{label-key: summed-ranks-value} for a labeled counter family."""
    fam = metrics.get(name)
    out = {}
    for key, s in (fam or {}).get("samples", {}).items():
        ranks = s.get("ranks", {})
        out[key] = sum(float(v) for v in ranks.values())
    return out


def _labeled_max(metrics: dict, name: str) -> dict:
    """{label-key: max-across-ranks value} for a labeled gauge family."""
    fam = metrics.get(name)
    out = {}
    for key, s in (fam or {}).get("samples", {}).items():
        v = s.get("max")
        if v is None:
            v = s.get("mean")
        if v is not None:
            out[key] = float(v)
    return out


def _label_of(key: str, name: str) -> str:
    labels = dict(
        item.partition("=")[::2] for item in key.split(",") if item)
    return labels.get(name, key)


def slo_pane(metrics: dict) -> list:
    """The SLO-plane lines (ISSUE 16's objective registry made live):
    per-objective burn rate + remaining error budget, worst offender
    named — empty when no registry publishes the gauges."""
    burn = _labeled_max(metrics, "slo_burn_rate")
    remaining = _labeled_max(metrics, "slo_budget_remaining")
    if not burn and not remaining:
        return []
    lines = ["SLO:"]
    worst = None
    for key in sorted(set(burn) | set(remaining)):
        obj = _label_of(key, "objective")
        b = burn.get(key)
        rank_b = float("inf") if b is not None and b < 0 else b
        burning = rank_b is not None and rank_b >= 1.0
        lines.append(
            f"  {obj}: burn {_fmt_v(b)}x, "
            f"budget left {_fmt_v(remaining.get(key))}"
            + ("  BURNING" if burning else ""))
        if rank_b is not None and (worst is None or rank_b > worst[1]):
            worst = (obj, rank_b)
    if worst is not None:
        lines.append(f"  worst offender: {worst[0]}")
    return lines


def serving_pane(metrics: dict) -> list:
    """The serving-plane lines (PR 12's engine made live): subscriber
    lag/staleness, queue depth + admission rejections, and per-arm request
    outcomes — empty when the fleet carries no serving series."""
    lag = _gauge_stat(metrics, "serving_subscriber_lag")
    if lag is None:
        lag = _gauge_stat(metrics, "serving_subscribe_lag_generations")
    stale = _gauge_stat(metrics, "serving_staleness_seconds")
    if stale is None:
        stale = _gauge_stat(metrics, "serving_subscribe_staleness_seconds")
    queue = _gauge_stat(metrics, "serving_queue_depth")
    rejected = _label_sums(metrics, "serving_admission_rejected")
    requests = _label_sums(metrics, "serving_requests")
    if lag is None and stale is None and queue is None \
            and not rejected and not requests:
        return []
    lines = ["SERVING:"]
    head = "  lag " + _fmt_v(lag) + " gen(s)"
    head += f", staleness {_fmt_v(stale)}s"
    head += f", queue depth {_fmt_v(queue)}"
    if rejected:
        total = int(sum(rejected.values()))
        by = " ".join(
            f"{k.replace('reason=', '')}={int(v)}"
            for k, v in sorted(rejected.items())
        )
        head += f", rejected {total} ({by})"
    lines.append(head)
    if requests:
        arms = {}
        for key, v in requests.items():
            labels = dict(
                item.partition("=")[::2] for item in key.split(",") if item
            )
            arm = labels.get("arm", "?")
            outcome = labels.get("outcome", "?")
            arms.setdefault(arm, {})[outcome] = int(v)
        for arm in sorted(arms):
            by = " ".join(
                f"{o}={n}" for o, n in sorted(arms[arm].items())
            )
            lines.append(f"  requests arm={arm}: {by}")
    # per-arm windowed latency quantiles (reqtrace gauges): the
    # TTFT/TPOT picture per rollout arm at a glance
    lat = {}
    for fam, field in (
        ("reqtrace_ttft_p50", "ttft_p50"),
        ("reqtrace_ttft_p99", "ttft_p99"),
        ("reqtrace_tpot_p50", "tpot_p50"),
        ("reqtrace_tpot_p99", "tpot_p99"),
    ):
        for key, v in _labeled_max(metrics, fam).items():
            lat.setdefault(_label_of(key, "arm"), {})[field] = v
    for arm in sorted(lat):
        d = lat[arm]
        lines.append(
            f"  latency arm={arm}: "
            f"ttft p50/p99 {_fmt_v(d.get('ttft_p50'))}s/"
            f"{_fmt_v(d.get('ttft_p99'))}s, "
            f"tpot p50/p99 {_fmt_v(d.get('tpot_p50'))}s/"
            f"{_fmt_v(d.get('tpot_p99'))}s")
    # hot-path rows (ISSUE 18): prefix-cache hit rate + page sharing and
    # speculative-decode acceptance — only when the engine emits them
    def _csum(name):
        fam = metrics.get(name)
        if not fam:
            return None
        return sum(
            sum(float(v) for v in s.get("ranks", {}).values())
            for s in fam.get("samples", {}).values())

    hits = _csum("serving_prefix_hits")
    misses = _csum("serving_prefix_misses")
    if hits is not None or misses is not None:
        h, m = hits or 0.0, misses or 0.0
        rate = h / (h + m) if (h + m) else 0.0
        row = (f"  prefix cache: hit rate {rate * 100:.1f}% "
               f"({int(h)}/{int(h + m)})")
        shared = _gauge_stat(metrics, "serving_prefix_pages_shared")
        if shared is not None:
            row += f", pages shared {int(shared)}"
        evicted = _csum("serving_prefix_evictions")
        if evicted:
            row += f", evicted {int(evicted)}"
        lines.append(row)
    proposed = _csum("spec_proposed")
    if proposed:
        accepted = _csum("spec_accepted") or 0.0
        row = (f"  spec decode: acceptance {accepted / proposed * 100:.1f}% "
               f"({int(accepted)}/{int(proposed)})")
        rollbacks = _csum("spec_rollbacks")
        if rollbacks:
            row += f", rollbacks {int(rollbacks)}"
        lines.append(row)
    return lines


_REPLICA_STATES = {0: "healthy", 1: "stale", 2: "draining", 3: "dead",
                   4: "drained"}


def fleet_serving_pane(metrics: dict) -> list:
    """The fleet-serving lines (ISSUE 17's replica tier made live):
    rollout epoch + stable/canary generations, hedge/failover/outcome
    counts, the backpressure hint, and one row per replica (queue depth,
    pages, staleness, state) — empty when no fleet router publishes the
    series."""
    epoch = _gauge_stat(metrics, "fleet_serving_rollout_epoch")
    states = _labeled_max(metrics, "fleet_serving_replica_state")
    requests = _label_sums(metrics, "fleet_requests")
    if epoch is None and not states and not requests:
        return []
    lines = ["FLEET-SERVING:"]
    head = f"  rollout epoch {_fmt_v(epoch)}"
    head += (f", stable gen "
             f"{_fmt_v(_gauge_stat(metrics, 'fleet_serving_stable_generation'))}")
    head += (f", canary gen "
             f"{_fmt_v(_gauge_stat(metrics, 'fleet_serving_canary_generation'))}")
    hedged = _label_sums(metrics, "fleet_requests_hedged")
    failed = _label_sums(metrics, "fleet_requests_failed_over")
    if hedged:
        head += f", hedged {int(sum(hedged.values()))}"
    if failed:
        head += f", failed over {int(sum(failed.values()))}"
    hint = _gauge_stat(metrics, "fleet_backpressure_hint_seconds")
    if hint is not None:
        head += f", backpressure hint {_fmt_v(hint)}s"
    lines.append(head)
    if requests:
        arms = {}
        for key, v in requests.items():
            labels = dict(
                item.partition("=")[::2] for item in key.split(",")
                if item)
            arms.setdefault(labels.get("arm", "?"), {})[
                labels.get("outcome", "?")] = int(v)
        for arm in sorted(arms):
            by = " ".join(
                f"{o}={n}" for o, n in sorted(arms[arm].items()))
            lines.append(f"  requests arm={arm}: {by}")
    queue = _labeled_max(metrics, "fleet_serving_replica_queue_depth")
    pages = _labeled_max(metrics, "fleet_serving_replica_pages_in_use")
    stale = _labeled_max(
        metrics, "fleet_serving_replica_staleness_seconds")
    for key in sorted(states):
        rid = _label_of(key, "replica")
        state = _REPLICA_STATES.get(int(states[key]), "?")
        qk = next((k for k in queue if _label_of(k, "replica") == rid),
                  None)
        pk = next((k for k in pages if _label_of(k, "replica") == rid),
                  None)
        sk = next((k for k in stale if _label_of(k, "replica") == rid),
                  None)
        lines.append(
            f"  replica {rid}: queue "
            f"{_fmt_v(queue.get(qk)) if qk else '--'}, pages "
            f"{_fmt_v(pages.get(pk)) if pk else '--'}, staleness "
            f"{_fmt_v(stale.get(sk)) + 's' if sk else '--'}, "
            f"state {state}")
    return lines


def control_plane_pane(metrics: dict) -> list:
    """The control-plane lines (ISSUE 19's HA rendezvous made live):
    KV role, fencing epoch, replication lag in WAL entries, and the
    failover count — empty when no rendezvous server publishes the
    role gauge."""
    role_v = _gauge_stat(metrics, "rendezvous_role")
    if role_v is None:
        return []
    role = {0: "primary", 1: "standby", 2: "deposed"}.get(
        int(role_v), f"role={role_v}")
    epoch = _gauge_stat(metrics, "rendezvous_fencing_epoch")
    lag = _gauge_stat(metrics, "rendezvous_replication_lag_entries")
    failovers = _gauge_stat(metrics, "rendezvous_failovers")
    wal = _gauge_stat(metrics, "rendezvous_wal_records")
    lines = ["CONTROL PLANE:"]
    head = f"  kv {role}, fencing epoch {_fmt_v(epoch) if epoch is not None else 0}"
    if lag is not None:
        head += f", replication lag {_fmt_v(lag)} entries"
        if lag > 0:
            head += "  LAGGING"
    if failovers:
        head += f", failovers {int(failovers)}"
    if wal is not None:
        head += f", wal records {_fmt_v(wal)}"
    lines.append(head)
    if role == "deposed":
        lines.append(
            "  DEPOSED: this server lost a fencing election; "
            "its writes are rejected (409)")
    return lines


def input_pane(metrics: dict) -> list:
    """The input-plane lines (ISSUE 15's pipeline made live): per-rank
    data wait / delivered examples-per-second, prefetch-watchdog stalls,
    and quarantined-shard counts — empty when the fleet carries no input
    series."""
    wait_fam = metrics.get("data_wait_seconds_recent")
    eps_fam = metrics.get("input_examples_per_second")
    quarantined = _gauge_stat(metrics, "data_quarantined_shards")
    stalls = _label_sums(metrics, "data_prefetch_stalls")
    substituted = _label_sums(metrics, "data_samples_substituted")
    if wait_fam is None and eps_fam is None and quarantined is None \
            and not stalls and not substituted:
        return []
    lines = ["INPUT:"]
    head = "  data wait " + _fmt_v(
        _gauge_stat(metrics, "data_wait_seconds_recent")) + "s (max)"
    head += f", {_fmt_v(_gauge_stat(metrics, 'input_examples_per_second', 'min'))} ex/s (min)"
    if stalls:
        head += f", stalls {int(sum(stalls.values()))}"
    if quarantined:
        head += f", quarantined shards {_fmt_v(quarantined)}"
    if substituted:
        head += f", substituted samples {int(sum(substituted.values()))}"
    lines.append(head)
    # per-rank wait row: the input-vs-compute split at a glance — the
    # rank whose wait stands out is input-bound, not a slow chip
    ranks = {}
    for fam, label in ((wait_fam, "wait"), (eps_fam, "ex/s")):
        for s in (fam or {}).get("samples", {}).values():
            for r, v in s.get("ranks", {}).items():
                ranks.setdefault(r, {})[label] = v
    if ranks and any("wait" in v for v in ranks.values()):
        per = " ".join(
            f"r{r}={_fmt_v(ranks[r].get('wait'))}s"
            for r in sorted(ranks, key=lambda x: int(x))
        )
        lines.append(f"  per-rank wait: {per}")
    return lines


def _fmt_v(v) -> str:
    if v is None:
        return "-"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if f == int(f) and abs(f) < 1e12:
        return str(int(f))
    return f"{f:.4g}"


def render(fleet: dict, *, is_fleet: bool = True,
           name_filter: str = "", max_ranks: int = 8) -> str:
    """One screenful of the fleet view as plain text (tested directly —
    the ANSI refresh loop just reprints this)."""
    lines = []
    ranks = fleet.get("ranks", [])
    dead = fleet.get("dead_ranks", [])
    head = (
        f"hvd_top — {time.strftime('%H:%M:%S')} — "
        f"{len(ranks)} rank(s) reporting"
        + (f", {len(dead)} DEAD: {dead}" if dead else "")
        + ("" if is_fleet else "  [single-process view: no fleet aggregator]")
    )
    lines.append(head)
    s = fleet.get("straggler")
    if s:
        lines.append(
            f"STRAGGLER: rank {s['rank']} trailing by "
            f"{s['spread_seconds'] * 1e3:.1f} ms "
            f"(op {s.get('op', '?')}, key {s.get('key')}, "
            f"streak {s.get('streak', 1)})"
        )
    else:
        lines.append("straggler: none detected")
    pane = slo_pane(fleet.get("metrics", {}))
    if pane:
        lines.extend(pane)
    pane = serving_pane(fleet.get("metrics", {}))
    if pane:
        lines.extend(pane)
    pane = fleet_serving_pane(fleet.get("metrics", {}))
    if pane:
        lines.extend(pane)
    pane = control_plane_pane(fleet.get("metrics", {}))
    if pane:
        lines.extend(pane)
    pane = input_pane(fleet.get("metrics", {}))
    if pane:
        lines.extend(pane)
    lines.append("")
    rank_cols = [str(r) for r in ranks][:max_ranks]
    header = (
        f"{'METRIC':<46} {'MIN':>10} {'MEAN':>10} {'MAX':>10} {'P99':>10}  "
        + " ".join(f"r{r:>3}" for r in rank_cols)
    )
    lines.append(header)
    lines.append("-" * len(header))
    metrics = fleet.get("metrics", {})
    for name in sorted(metrics):
        if name_filter and name_filter not in name:
            continue
        fam = metrics[name]
        for key in sorted(fam.get("samples", {})):
            sample = fam["samples"][key]
            label = f"{name}{{{key}}}" if key else name
            if len(label) > 46:
                label = label[:43] + "..."
            if fam["type"] == "histogram":
                lines.append(
                    f"{label:<46} {'·':>10} "
                    f"{_fmt_v(sample['sum'] / sample['count'] if sample.get('count') else None):>10} "
                    f"{'·':>10} {_fmt_v(sample.get('p99')):>10}  "
                    f"n={sample.get('count', 0)}"
                )
            else:
                per_rank = " ".join(
                    f"{_fmt_v(sample['ranks'].get(r)):>4}"
                    for r in rank_cols
                )
                lines.append(
                    f"{label:<46} {_fmt_v(sample.get('min')):>10} "
                    f"{_fmt_v(sample.get('mean')):>10} "
                    f"{_fmt_v(sample.get('max')):>10} "
                    f"{_fmt_v(sample.get('p99')):>10}  {per_rank}"
                )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--url", default="http://127.0.0.1:9090",
        help="rank-0 metrics endpoint (HOROVOD_METRICS_PORT)",
    )
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh cadence in seconds")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (scripts/tests)")
    p.add_argument("--json", action="store_true",
                   help="print the raw fleet JSON instead of the table")
    p.add_argument("--filter", default="",
                   help="only show metrics whose name contains this")
    p.add_argument("--max-ranks", type=int, default=8,
                   help="per-rank value columns to show")
    args = p.parse_args(argv)

    while True:
        try:
            fleet, is_fleet = fetch(args.url)
        except (OSError, urllib.error.URLError, ValueError) as e:
            print(f"hvd_top: cannot scrape {args.url}: {e}", file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        if args.json:
            print(json.dumps(fleet, indent=1))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(render(fleet, is_fleet=is_fleet,
                         name_filter=args.filter,
                         max_ranks=args.max_ranks))
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
