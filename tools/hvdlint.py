#!/usr/bin/env python
"""hvdlint: static analysis for collective-schedule + threading discipline.

Runs the ``HVD0xx`` rule engine (:mod:`horovod_tpu.analysis.lint`) over
Python sources and reports findings with rule id, location, and a fix
hint. Exit status 1 when any unwaived finding survives — wire it into CI
(the tier-1 self-lint test does exactly that over ``horovod_tpu/``,
``tools/`` and ``examples/``).

Usage::

    python tools/hvdlint.py horovod_tpu tools examples
    python tools/hvdlint.py --json horovod_tpu        # machine-readable
    python tools/hvdlint.py --list-rules              # the catalog
    python tools/hvdlint.py --waivers my_waivers.txt src/

Waivers: central file (default ``tools/hvdlint_waivers.txt`` next to this
script, when present) with ``<rule> <path-glob>[:line] <reason>`` lines,
plus inline ``# hvdlint: waive=HVD00x reason`` comments. See
``docs/static_analysis.md`` for the catalog and rationale.

stdlib + the lint module only — no JAX import, safe in any CI venv.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _load_lint_module():
    """Load ``analysis/lint.py`` straight from its file, bypassing the
    ``horovod_tpu`` package ``__init__`` (which imports JAX): the linter
    must start fast and run in any venv, JAX installed or not."""
    import importlib.util

    path = os.path.join(_ROOT, "horovod_tpu", "analysis", "lint.py")
    spec = importlib.util.spec_from_file_location("_hvdlint_rules", path)
    mod = importlib.util.module_from_spec(spec)
    # register before exec: dataclass processing resolves cls.__module__
    # through sys.modules
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_lint = _load_lint_module()
RULES = _lint.RULES
lint_paths = _lint.lint_paths
load_waivers = _lint.load_waivers

DEFAULT_WAIVERS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "hvdlint_waivers.txt"
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdlint", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "paths", nargs="*", default=[],
        help="files or directories to lint (default: horovod_tpu tools "
             "examples under the repo root)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as a JSON array",
    )
    parser.add_argument(
        "--waivers", default=None,
        help=f"central waivers file (default: {DEFAULT_WAIVERS} when it "
             f"exists)",
    )
    parser.add_argument(
        "--no-waivers", action="store_true",
        help="ignore every waiver (audit mode: see what is being waived)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES):
            summary, hint = RULES[rule]
            print(f"{rule}: {summary}\n    fix: {hint}")
        return 0

    paths = args.paths or [
        os.path.join(_ROOT, d) for d in ("horovod_tpu", "tools", "examples")
    ]
    waivers = []
    if not args.no_waivers:
        waiver_path = args.waivers or (
            DEFAULT_WAIVERS if os.path.exists(DEFAULT_WAIVERS) else None
        )
        if waiver_path:
            waivers = load_waivers(waiver_path)

    findings = lint_paths(paths, waivers)
    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        if findings:
            print(f"\n{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
