#!/usr/bin/env python3
"""hvd_blackbox — offline hang forensics from flight-recorder sidecars.

When a job died or hung and every process is already gone, the per-rank
flight sidecars (``HOROVOD_FLIGHT_DIR``, written by
:mod:`horovod_tpu.observability.flight`) are what is left. This tool
replays the SAME diagnosis the live watchdog runs — merge the per-rank
streams, shift each onto the KV-server timebase by its header's clock
offset, find the frontier collective ``(step, gen, seq)``, and say which
rank(s) never arrived (or whose schedule diverged) — plus a unified
human-readable timeline of the final events per rank.

Usage::

    python tools/hvd_blackbox.py /path/to/flight_dir
    python tools/hvd_blackbox.py flight-rank0.jsonl flight-rank1.jsonl
    python tools/hvd_blackbox.py /path/to/flight_dir --json
    python tools/hvd_blackbox.py /path/to/flight_dir --tail 40

Exit status: 0 when the record shows forward progress, 3 when a hang or
divergence verdict was reached (scriptable, like ``grep``), 1 on usage or
read errors.

stdlib + the (stdlib-only) observability package — running forensics on a
dead job's artifacts must never require a live backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from horovod_tpu.observability import flight  # noqa: E402


def _fmt_event(ev: dict) -> str:
    t = ev.get("t")
    ts = f"{t:12.6f}" if isinstance(t, (int, float)) else " " * 12
    kind = ev.get("kind", "?")
    if kind == "collective":
        return (
            f"{ts}  {ev.get('ph', '?')} {ev.get('op', '?'):<14} "
            f"step={ev.get('step')} gen={ev.get('gen')} seq={ev.get('seq')}"
        )
    rest = {
        k: v for k, v in ev.items() if k not in ("t", "kind")
    }
    return f"{ts}  {kind:<16} {json.dumps(rest, separators=(',', ':'))}"


def request_summary(rank_events) -> list:
    """Group the serve-kind ``req_begin``/``req_end`` events
    (:mod:`horovod_tpu.observability.reqtrace` mirrors every request's
    lifecycle into the flight ring with its rid) per request, and name
    the STRANDED ones — begun but never ended in the record. A hang
    diagnosis can then say which in-flight requests the hang took with
    it. Empty when the record carries no request events."""
    begun: dict = {}
    ended = 0
    relabels: dict = {}
    for r in sorted(rank_events):
        for ev in rank_events[r]:
            if ev.get("kind") != "serve":
                continue
            what = ev.get("what")
            rid = ev.get("rid")
            if rid is None:
                continue
            if what == "req_begin":
                begun[rid] = ev
            elif what == "req_end":
                if begun.pop(rid, None) is not None:
                    ended += 1
            elif what == "req_relabel":
                relabels[rid] = ev
    if not begun and not ended:
        return []
    lines = [
        f"requests in record: {ended + len(begun)} begun, "
        f"{ended} completed, {len(begun)} STRANDED"
    ]
    for rid in sorted(begun, key=str):
        ev = begun[rid]
        arm = relabels.get(rid, ev).get("dst", ev.get("arm", "?"))
        t = ev.get("t")
        ts = f" (begun t={t:.6f})" if isinstance(t, (int, float)) else ""
        lines.append(f"  STRANDED request {rid} on arm {arm}{ts}")
    return lines


def failover_events(rank_events) -> list:
    """Every ``kind == "failover"`` event in the record (the FAILOVER
    flight mark :func:`horovod_tpu.run.replication.promote` writes when
    a standby takes over the rendezvous KV), sorted by corrected time."""
    out = []
    for r in rank_events:
        for ev in rank_events[r]:
            if ev.get("kind") == "failover":
                out.append(ev)
    out.sort(key=lambda e: e.get("t") or 0.0)
    return out


def failover_annotation(rank_events, verdict) -> str:
    """One line of context when a hang verdict's window spans a KV
    failover: the ranks did not stall on a peer — the control plane was
    lost (and possibly re-elected) under them. Empty string otherwise."""
    if verdict.get("verdict") not in (
        "rank_missing", "all_parked", "schedule_divergence",
    ):
        return ""
    fos = failover_events(rank_events)
    if not fos:
        return ""
    # the hang window opens at the last event any rank managed to write;
    # a failover at-or-after that point means the stall coincides with
    # control-plane loss, not a slow or dead peer rank
    last_t = 0.0
    for r in rank_events:
        for ev in rank_events[r]:
            if ev.get("kind") == "failover":
                continue
            t = ev.get("t")
            if isinstance(t, (int, float)) and t > last_t:
                last_t = t
    spanning = [
        ev for ev in fos
        if not isinstance(ev.get("t"), (int, float)) or ev["t"] >= last_t
    ]
    if not spanning:
        return ""
    ev = spanning[-1]
    epoch = ev.get("epoch", "?")
    reason = ev.get("reason") or "unspecified"
    return (
        f"NOTE: control-plane loss — a rendezvous KV failover "
        f"(fencing epoch -> {epoch}, reason: {reason}) falls inside the "
        f"hang window; the stall is control-plane recovery, not a "
        f"peer-rank hang"
    )


def render(rank_events, meta, verdict, *, tail: int = 20) -> str:
    """The human report: per-file load notes, the last `tail` events per
    rank on the corrected timebase, the per-request grouping (stranded
    in-flight requests named), and the verdict line."""
    lines = []
    lines.append("hvd_blackbox — flight-recorder forensics")
    for f in meta.get("files", []):
        if "error" in f:
            lines.append(f"  file {f['path']}: UNREADABLE ({f['error']})")
            continue
        note = f" ({f['skipped']} torn/corrupt line(s) skipped)" \
            if f.get("skipped") else ""
        lines.append(
            f"  file {f['path']}: ranks {f['ranks']}, "
            f"{f['events']} event(s){note}"
        )
    lines.append("")
    for r in sorted(rank_events):
        evs = rank_events[r][-tail:]
        lines.append(f"rank {r} — last {len(evs)} event(s):")
        for ev in evs:
            lines.append("  " + _fmt_event(ev))
        lines.append("")
    for r in sorted(
        set(range(meta.get("world", 0))) - set(rank_events)
    ):
        lines.append(f"rank {r} — NO RECORD (no sidecar, no events)")
    reqs = request_summary(rank_events)
    if reqs:
        lines.extend(reqs)
        lines.append("")
    lines.append("")
    lines.append(f"VERDICT: {flight.describe(verdict)}")
    note = failover_annotation(rank_events, verdict)
    if note:
        lines.append(note)
    lk = verdict.get("last_key") or {}
    for r in sorted(lk, key=int):
        lines.append(f"  rank {r}: last collective begun = {lk[r]}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "paths", nargs="+",
        help="flight sidecar directory (globbed for flight-rank*.jsonl) "
             "or individual sidecar files",
    )
    p.add_argument("--json", action="store_true",
                   help="print the raw verdict JSON instead of the report")
    p.add_argument("--tail", type=int, default=20,
                   help="events shown per rank in the timeline")
    args = p.parse_args(argv)

    paths = args.paths[0] if len(args.paths) == 1 else args.paths
    try:
        rank_events, meta = flight.load_dir(paths)
    except OSError as e:
        print(f"hvd_blackbox: cannot read {paths}: {e}", file=sys.stderr)
        return 1
    if not rank_events:
        print(
            f"hvd_blackbox: no flight events found under {paths}",
            file=sys.stderr,
        )
        return 1
    verdict = flight.analyze_loaded(rank_events, meta)
    note = failover_annotation(rank_events, verdict)
    if note:
        verdict = dict(verdict, failover_note=note)
    if args.json:
        print(json.dumps(verdict, indent=1))
    else:
        print(render(rank_events, meta, verdict, tail=args.tail))
    return 3 if verdict.get("verdict") in (
        "rank_missing", "schedule_divergence", "all_parked",
    ) else 0


if __name__ == "__main__":
    sys.exit(main())
