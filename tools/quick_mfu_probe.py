#!/usr/bin/env python
"""Fastest-possible real-TPU signal: bf16 matmul TFLOP/s + MFU.

The axon tunnel's healthy windows can be minutes long — too short for a
full ResNet benchmark (compile alone is 20-40 s). This probe compiles one
8192x8192x8192 bf16 matmul (~1.1 TFLOP), loops it, and reports achieved
TFLOP/s and MFU against the chip's peak — proving the toolchain executed
on real hardware and giving the first absolute perf number of the round.
Runs in well under a minute after backend init. Emits ONE JSON line.
"""

import argparse
import json
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--dim", type=int, default=8192)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--hbm-mb", type=int, default=1024,
                   help="bandwidth-sample buffer size (bf16), >> VMEM")
    p.add_argument("--hbm-iters", type=int, default=20)
    args = p.parse_args()

    sys.path.insert(0, ".")
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()  # watchdog SIGTERM -> clean device teardown

    import jax
    import jax.numpy as jnp

    from horovod_tpu.profiler import device_peak_flops, device_peak_hbm_bytes

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "?")
    peak_flops = device_peak_flops(kind)  # None for untabled kinds (cpu)
    peak = peak_flops / 1e12 if peak_flops else None
    peak_hbm = device_peak_hbm_bytes(kind)

    n = args.dim
    key1, key2 = jax.random.split(jax.random.PRNGKey(0))
    # Scale by 1/sqrt(n) so the chained mm(a, out) loop keeps row norms ~1:
    # each product then stays O(1) instead of growing ~sqrt(n)x per iteration
    # and overflowing bf16 to inf within a few iterations (which would make
    # the fenced readback meaningless and could hit non-finite slow paths).
    # The chain itself stays — it defeats CSE across iterations.
    a = jax.random.normal(key1, (n, n), jnp.bfloat16) * (1.0 / n**0.5)
    b = jax.random.normal(key2, (n, n), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return a @ b

    out = mm(a, b)  # compile
    # device->host read: block_until_ready alone has been observed not to
    # fence on the tunneled runtime, for warm-up and timed loop alike
    float(out[0, 0].astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = mm(a, out)
    float(out[0, 0].astype(jnp.float32))
    dt = (time.perf_counter() - t0) / args.iters
    tflops = 2 * n * n * n / dt / 1e12

    # HBM bandwidth: a memory-bound elementwise chain on a buffer far
    # bigger than VMEM (read + write per element). The usual TPU bottleneck
    # is HBM, not the MXU — measure both while the chip is answering.
    hbm_gbps = None
    try:
        m = args.hbm_mb * (1 << 20) // 2  # bf16 elements
        x = jnp.ones((m,), jnp.bfloat16)

        # donation is load-bearing: async dispatch enqueues the whole loop
        # before the device drains, and without aliasing each call would
        # hold its own 1 GiB output while its input stays pinned —
        # hbm_iters+1 GiB in flight, RESOURCE_EXHAUSTED on a 16 GiB chip
        bump = jax.jit(lambda x: x + jnp.bfloat16(1.0), donate_argnums=0)

        x = bump(x)  # compile
        float(x[0].astype(jnp.float32))
        t0 = time.perf_counter()
        for _ in range(args.hbm_iters):
            x = bump(x)
        float(x[0].astype(jnp.float32))
        dt_h = (time.perf_counter() - t0) / args.hbm_iters
        hbm_gbps = round(2 * 2 * m / dt_h / 1e9, 1)  # rd+wr, 2 B/elem
    except Exception as e:
        # bandwidth sample is auxiliary; never fail the MFU capture
        print(f"hbm bandwidth sample failed: {e}", file=sys.stderr)

    print(json.dumps({
        "metric": "bf16_matmul_tflops",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        "device_kind": kind,
        "platform": dev.platform,
        "dim": n,
        "ms_per_matmul": round(dt * 1e3, 3),
        "mfu_vs_peak": round(tflops / peak, 4) if peak else None,
        "peak_assumed": peak,
        "hbm_gbps": hbm_gbps,
        "hbm_buffer_mb": args.hbm_mb if hbm_gbps else None,
        "hbm_frac_vs_peak": (
            round(hbm_gbps * 1e9 / peak_hbm, 4)
            if hbm_gbps and peak_hbm else None),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
