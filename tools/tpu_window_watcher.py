#!/usr/bin/env python
"""Round-long TPU window watcher: convert ANY healthy minute into a number.

The axon-tunnel TPU in this environment oscillates between healthy and
wedged on a timescale of hours, and a wedged backend can hang even
``jax.devices()``.  Four rounds of end-of-round ``bench.py`` invocations
produced ``value=null`` because the single probe window happened to land
on a wedge.  This watcher inverts the strategy: it runs for the WHOLE
round, probing the backend in a throwaway subprocess every ``--interval``
seconds, and the moment a probe answers it climbs an escalation ladder of
benchmark rungs cheapest-first, each in its own watchdogged child:

    1. mfu     tools/quick_mfu_probe.py        (<1 min after init)
    2. flash   tools/flash_onchip_check.py     (Pallas kernel on-chip)
    3. trace   XLA device trace of a matmul loop (artifact for overlap
               judging — the reference Timeline's analog evidence)
    4. resnet  bench.py small-iter ResNet-50 img/s (the headline metric)

Every rung that completes writes its JSON line to ``--artifacts``
(default ``.tpu_watch/``) with a timestamp; ``bench.py`` merges the best
artifacts into its final output, so a number captured at hour 2 survives
a chip that is wedged again at hour 12.

Children are started in their own session and killed by process group on
timeout (``bench.py`` spawns a grandchild; killing only the child would
orphan a wedged grandchild holding the tunnel).

Usage:  mkdir -p .tpu_watch && \
        nohup python tools/tpu_window_watcher.py >> .tpu_watch/watch.log 2>&1 &
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# horovod_tpu.resilience.loop.RESUMABLE_EXIT_CODE (75 = BSD EX_TEMPFAIL):
# a child that exits with it was *preempted* — it drained, wrote an
# emergency checkpoint, and wants a retry — not failed. A literal, not an
# import: this watcher must never import the package in-process (that
# pulls in jax, whose backend init can hang on the very wedge being
# watched for).
RESUMABLE_EXIT_CODE = 75

# horovod_tpu.resilience.elastic logs this prefix on every membership
# change. A rung that hits its watchdog budget WHILE having just resized
# (a rank died, the survivors re-formed the mesh and are replaying from
# the rollback snapshot) is making healthy progress, not wedged — it gets
# a bounded extension per newly observed resize instead of the kill.
ELASTIC_RESIZE_MARKER = "elastic: resized to world size"
ELASTIC_MAX_EXTENSIONS = 2


def count_elastic_resizes(text) -> int:
    """Elastic resize log lines in a child's captured output so far."""
    return (text or "").count(ELASTIC_RESIZE_MARKER)

PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "print(len(d), d[0].platform, getattr(d[0], 'device_kind', '?'))"
)

TRACE_CODE = """\
import json, signal, sys, time
signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
sys.path.insert(0, ".")
import jax, jax.numpy as jnp
from horovod_tpu.profiler import timeline
n = 4096
a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16) * (1.0 / n**0.5)
mm = jax.jit(lambda a, b: a @ b)
out = mm(a, a)
float(out[0, 0].astype(jnp.float32))
trace_dir = sys.argv[1]
t0 = time.perf_counter()
with timeline(trace_dir):
    for _ in range(20):
        out = mm(a, out)
    float(out[0, 0].astype(jnp.float32))
dt = time.perf_counter() - t0
d = jax.devices()[0]
print(json.dumps({
    "metric": "xla_device_trace_captured", "value": round(dt, 3), "unit": "s",
    "trace_dir": trace_dir, "platform": d.platform,
    "device_kind": getattr(d, "device_kind", "?"),
}))
"""


LOG_STREAM = None  # None -> stdout; "stderr" -> CURRENT sys.stderr (late
#                    binding: bench.py uses this so its own stdout stays a
#                    single parseable JSON line — a pinned stream object
#                    would go stale when the host process swaps/closes
#                    stderr, e.g. pytest capture)


def log(msg: str) -> None:
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    stream = sys.stderr if LOG_STREAM == "stderr" else (LOG_STREAM
                                                        or sys.stdout)
    try:
        print(f"[{ts}] {msg}", file=stream, flush=True)
    except (ValueError, OSError):  # closed stream / dead pipe reader;
        pass  # logging must never kill the watch


def probe(timeout_s: int) -> str | None:
    """One throwaway-subprocess health check; returns device string or None.

    NOT subprocess.run: its TimeoutExpired handler calls wait() with no
    timeout after kill(), and a probe child wedged in an uninterruptible
    device call survives SIGKILL until the syscall returns — that unbounded
    wait would freeze the watcher on the very condition it exists to ride
    out. Bounded reap, same as run_rung.
    """
    proc = subprocess.Popen(
        [sys.executable, "-c", PROBE_CODE],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, start_new_session=True,
    )
    try:
        stdout, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass  # D-state child; abandon, don't block the watch loop
        return None
    if proc.returncode == 0 and stdout.strip():
        parts = stdout.split()
        if len(parts) >= 2 and parts[1] == "cpu":
            # plugin fell back to CPU: the tunnel is NOT healthy, and a
            # ladder climbed now would benchmark the host
            log("probe answered from CPU fallback — treating as wedged")
            return None
        return stdout.strip()
    return None


#: artifact max age (s) shared by bench's merge, the projection's measured-
#: MFU lookup, and the watcher's restart seeding — ONE freshness policy so
#: a capture a consumer would discard can never suppress a re-capture
FRESHNESS_S = 13 * 3600


def iter_fresh_artifacts(art_dir: str, max_age_s: float = FRESHNESS_S):
    """Yield ``(path, data)`` for every parseable artifact younger than
    ``max_age_s`` (file mtime), sorted by filename (== capture time)."""
    import glob

    now = time.time()
    for path in sorted(glob.glob(os.path.join(art_dir, "*.json"))):
        try:
            if now - os.path.getmtime(path) > max_age_s:
                continue
            with open(path) as f:
                data = json.load(f)
        except (ValueError, OSError):
            continue
        yield path, data


def jax_cache_env(artifacts: str, base: dict = None) -> dict:
    """Child env with the persistent XLA compilation cache enabled under
    ``artifacts``/jax_cache. One cache shared by every rung child AND the
    end-of-round driver bench: a healthy window spent compiling ResNet-50
    pays that cost once; the next window hits disk and goes straight to
    measurement. Critical when windows are shorter than first-compile."""
    env = dict(os.environ if base is None else base)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(artifacts, "jax_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    return env


def artifact_ok(data: dict) -> bool:
    """The shared acceptance policy for a persisted rung artifact: the rung
    completed (rc 0 — run_rung maps recovered-from-kill completions to 0),
    measured something (non-null value), and measured it ON HARDWARE — a
    child that lost the chip between probe and backend init falls back to
    CPU and completes plausibly, but that is a host number, not a TPU one.
    bench._best_artifacts and scaling_projection._resolve_mfu apply this
    same predicate so the policies cannot drift."""
    if data.get("_rc", 0) != 0 or data.get("value") is None:
        return False
    if data.get("platform") == "cpu" or data.get("device_kind") == "cpu":
        return False
    return True


def rung_active_file(artifacts: str) -> str:
    """Lease file for a rung currently holding the chip: ``"<pid>
    <timeout_s>"`` (older cores wrote the bare pid). bench.py waits on it
    before its own probe so the end-of-round driver window never runs two
    backend inits against the tunnel at once, and derives its staleness
    threshold from the recorded timeout instead of a hardwired constant."""
    return os.path.join(artifacts, "ACTIVE")


def _txt(x) -> str:
    """TimeoutExpired carries partial output as bytes or str depending on
    the Python build; normalize (None -> '')."""
    return x.decode("utf-8", "replace") if isinstance(x, bytes) else (x or "")


def run_rung(name: str, cmd: list, timeout_s: int, artifacts: str):
    """Run one ladder rung in a watchdogged child; persist its JSON line.

    Returns the parsed JSON dict on success, else None.  The artifact is
    saved whenever a JSON line was produced at all — a kernel *failure*
    report is evidence too.  A child killed by the watchdog still succeeds
    if it had already printed+flushed a complete result line with a
    non-null value (bench.py prints the headline img/s BEFORE its optional
    trace capture precisely for this): the measurement finished, only the
    process didn't.  ``run_rung.last_timed_out`` records whether this call
    actually killed a child mid-operation (callers use it to give the
    tunnel a breather before re-probing).
    """
    log(f"rung {name}: {' '.join(cmd)}")
    run_rung.last_timed_out = False
    run_rung.last_preempted = False
    t0 = time.time()
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, start_new_session=True, env=jax_cache_env(artifacts),
    )
    active = rung_active_file(artifacts)
    try:
        with open(active, "w") as f:
            # pid + watchdog budget: bench derives lease staleness from the
            # recorded timeout (a fixed constant went stale the moment rung
            # budgets changed)
            f.write(f"{proc.pid} {timeout_s}")
    except OSError:
        pass
    timed_out = False
    seen_resizes = 0
    extensions = 0
    try:
        while True:
            try:
                stdout, stderr = proc.communicate(timeout=timeout_s)
                break
            except subprocess.TimeoutExpired as e:
                # An elastic resize line that appeared since the last check
                # is forward progress (membership change + replay, not a
                # wedge): extend the budget, bounded so a genuinely wedged
                # post-resize child still dies.
                n = count_elastic_resizes(_txt(e.stderr)) + \
                    count_elastic_resizes(_txt(e.stdout))
                if n > seen_resizes and extensions < ELASTIC_MAX_EXTENSIONS:
                    seen_resizes = n
                    extensions += 1
                    log(f"rung {name}: elastic resize observed "
                        f"({n} so far) — healthy progress, extending "
                        f"budget ({extensions}/{ELASTIC_MAX_EXTENSIONS})")
                    continue
                raise
    except subprocess.TimeoutExpired as e:
        # SIGTERM first: the children install a SIGTERM->SystemExit handler
        # (run/env_util.install_sigterm_exit), so a merely-SLOW child (e.g.
        # a long XLA compile) runs its finalizers and releases the device
        # client cleanly — SIGKILLing mid-device-operation has been observed
        # to wedge the tunnel for the probes that follow. A child truly
        # wedged in an uninterruptible C call ignores both; bounded reaps
        # throughout. Seed stdout/stderr from the exception's partial
        # capture NOW: when the post-kill reaps below also time out, the
        # already-flushed result line must not be lost with them.
        log(f"rung {name}: TIMEOUT after {timeout_s}s — SIGTERM, then kill")
        timed_out = True
        run_rung.last_timed_out = True
        stdout, stderr = _txt(e.stdout), _txt(e.stderr)
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            proc.terminate()
        try:
            stdout, stderr = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired as e2:
            stdout = _txt(e2.stdout) or stdout
            stderr = _txt(e2.stderr) or stderr
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            try:
                stdout, stderr = proc.communicate(timeout=15)
            except subprocess.TimeoutExpired as e3:
                # D-state child; keep the best partial capture we have
                stdout = _txt(e3.stdout) or stdout
                stderr = _txt(e3.stderr) or stderr
    finally:
        try:
            os.unlink(active)
        except OSError:
            pass
    dt = time.time() - t0
    run_rung.last_preempted = proc.returncode == RESUMABLE_EXIT_CODE
    line = next(
        (ln for ln in reversed((stdout or "").splitlines())
         if ln.startswith("{")),
        None,
    )
    if line is None:
        tail = (stderr or "").strip().splitlines()[-3:]
        kind = (
            "preempted, retry" if run_rung.last_preempted
            else f"rc={proc.returncode}"
        )
        log(f"rung {name}: no JSON ({kind}, {dt:.0f}s) {tail}")
        return None
    try:
        data = json.loads(line)
    except ValueError:
        log(f"rung {name}: unparseable JSON line (rc={proc.returncode})")
        return None
    complete = (data.get("value") is not None
                and (proc.returncode == 0 or timed_out)
                and not (data.get("platform") == "cpu"
                         or data.get("device_kind") == "cpu"))
    data["_rung"] = name
    # a complete measurement recovered from a killed-mid-extras child is a
    # success for the merge layer; _timed_out keeps the history honest.
    # CPU fallbacks stay captured-but-failed so the ladder retries the rung
    # on a later genuinely-healthy window instead of marking it succeeded.
    data["_rc"] = 0 if (complete and timed_out) else proc.returncode
    if timed_out:
        data["_timed_out"] = True
    data["_wall_s"] = round(dt, 1)
    data["_captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    ts = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
    path = os.path.join(artifacts, f"{name}_{ts}.json")
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    log(f"rung {name}: {'OK' if complete else 'captured-but-failed'} "
        f"({dt:.0f}s) -> {path}: {line[:200]}")
    return data if complete else None


run_rung.last_timed_out = False
run_rung.last_preempted = False


def reprobe_after_rung(probe_timeout: int = 45, wait_s: int = 60):
    """Probe after a failed rung.  If the rung was killed mid-operation
    (watchdog timeout), give the tunnel a breather first — probing
    immediately after reaping has read as "wedged" while the device was
    merely mid-recovery from the kill.  A rung that failed fast without
    touching the device skips the wait."""
    if run_rung.last_timed_out:
        time.sleep(wait_s)
    return probe(probe_timeout)


def build_rungs(artifacts: str, trace_dir: str = None,
                include_resnet: bool = True):
    """The shared escalation ladder, headline-first after the cheap probe.
    bench.py's end-of-round ladder reuses this (minus the resnet rung, which
    it runs itself with its own CLI args) so the two never drift.

    Rung order is by value-per-wedge-risk, not strictly by cost: the first
    healthy window of round 5 spent 8 min compiling the Pallas flash kernel
    (rung 2 at the time), timed out, and the window closed before the
    headline img/s rung ever ran.  The img/s metric is the one BENCH_r{N}
    leads with, so resnet now climbs right after the <1 min MFU probe and
    the flash kernel — auxiliary evidence with the slowest compile — goes
    last, at reduced shape so a healthy window can actually finish it."""
    py = sys.executable
    trace_dir = trace_dir or os.path.join(artifacts, "xla_trace")
    rungs = [
        ("mfu", [py, os.path.join(REPO, "tools", "quick_mfu_probe.py")], 300),
    ]
    if include_resnet:
        rungs.append(
            ("resnet", [py, os.path.join(REPO, "bench.py"), "--no-probe",
                        "--batch-size", "64", "--warmup", "3", "--iters",
                        "10", "--run-timeout", "900", "--trace-dir",
                        os.path.join(artifacts, "xla_trace_train")], 960))
    rungs += [
        # flagship TransformerLM (flash + RoPE) train tokens/s + MFU; sized
        # ~190M params so fp32 params+grads+opt state sit well inside v5e HBM
        ("lm", [py, os.path.join(REPO, "examples",
                                 "transformer_lm_benchmark.py"),
                "--dim", "1024", "--depth", "12", "--heads", "16",
                "--seq-len", "2048", "--batch", "8", "--steps", "12",
                "--warmup", "2", "--flash", "--rope"], 600),
        # the reference's core architectural claim, measured ON CHIP: async
        # named-tensor enqueue (background negotiation + grouped launches)
        # vs the in-jit ceiling. On TPU the per-device stream overlaps
        # dispatch with compute (no CPU serialization fence), so
        # core_vs_injit here is the overlap evidence the CPU mesh cannot give
        ("cpe2e", [py, os.path.join(REPO, "examples",
                                    "e2e_control_plane_bench.py"),
                   "--platform", "tpu", "--steps", "30", "--image-size", "64",
                   "--filters", "32", "--batch-per-dev", "16"], 600),
        ("trace", [py, "-c", TRACE_CODE, trace_dir], 300),
        ("flash",
         [py, os.path.join(REPO, "tools", "flash_onchip_check.py"),
          "--seq", "1024", "--iters", "5"], 600),
    ]
    return rungs


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--interval", type=int, default=600,
                   help="seconds between probes while rungs remain")
    p.add_argument("--idle-interval", type=int, default=1800,
                   help="seconds between probes once every rung has succeeded")
    p.add_argument("--probe-timeout", type=int, default=45)
    p.add_argument("--max-hours", type=float, default=11.5)
    p.add_argument("--artifacts", default=os.path.join(REPO, ".tpu_watch"))
    args = p.parse_args()

    os.makedirs(args.artifacts, exist_ok=True)
    rungs = build_rungs(args.artifacts)
    succeeded: set = set()
    # Seed from artifacts already banked this round: a restarted watcher
    # must not spend a scarce healthy window re-running a 10-minute rung it
    # already captured. Only artifacts that will STILL be inside the
    # consumers' FRESHNESS_S window when this watcher's run ends qualify —
    # seeding an artifact bench would later discard as stale (or, for the
    # img/s rung, as a different model) would suppress the re-capture while
    # losing the number. The <1 min mfu rung is exempt — it stays first in
    # every window for best-of sampling and as the cheap device check.
    seed_age = max(0.0, FRESHNESS_S - args.max_hours * 3600)
    for path, data in iter_fresh_artifacts(args.artifacts, seed_age):
        rung = data.get("_rung")
        if not rung or rung == "mfu" or not artifact_ok(data):
            continue
        if rung == "resnet" and not str(
                data.get("metric", "")).startswith("resnet50_"):
            continue  # the ladder's resnet rung benches resnet50
        succeeded.add(rung)
    if succeeded:
        log(f"seeded from banked artifacts: {sorted(succeeded)}")
    deadline = time.time() + args.max_hours * 3600
    log(f"watcher up: interval={args.interval}s artifacts={args.artifacts} "
        f"deadline in {args.max_hours}h")

    # Aggregate probe statistics, rewritten every loop iteration: the
    # round's proof of how many healthy windows actually occurred (the
    # "zero healthy windows all round" claim needs evidence, not absence).
    stats = {"probes": 0, "healthy": 0, "healthy_at": [],
             "started_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime())}

    def write_stats():
        stats["updated_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime())
        stats["rungs_succeeded"] = sorted(succeeded)
        try:
            with open(os.path.join(args.artifacts,
                                   "watch_summary.json"), "w") as f:
                json.dump(stats, f, indent=1)
        except OSError:
            pass

    pause_file = os.path.join(args.artifacts, "PAUSE")
    while time.time() < deadline:
        try:
            pause_age = time.time() - os.path.getmtime(pause_file)
        except OSError:
            pause_age = None
        if pause_age is not None and pause_age < 2 * 3600:
            # bench.py owns the chip right now (end-of-round driver run);
            # stay off it so two backend inits don't contend for the tunnel.
            log("paused (bench.py holds the chip)")
            time.sleep(60)
            continue
        if pause_age is not None:
            # bench.py was SIGKILLed past its finally block; a stale PAUSE
            # must not waste every remaining healthy window of the round.
            log(f"removing stale PAUSE (age {pause_age / 3600:.1f}h)")
            try:
                os.unlink(pause_file)
            except OSError:
                pass
        dev = probe(args.probe_timeout)
        stats["probes"] += 1
        if dev is None:
            log("probe: wedged")
        else:
            stats["healthy"] += 1
            stats["healthy_at"].append(
                time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
            log(f"probe: HEALTHY ({dev}) — climbing ladder")
            for name, cmd, timeout_s in rungs:
                if os.path.exists(pause_file):
                    log("PAUSE appeared mid-ladder; yielding the chip")
                    break
                if name in succeeded:
                    continue
                if run_rung(name, cmd, timeout_s, args.artifacts) is not None:
                    succeeded.add(name)
                elif run_rung.last_preempted:
                    # Preempted (EX_TEMPFAIL), not failed: the child
                    # drained, checkpointed, and asked for a retry — not
                    # evidence of a wedge, so no re-probe; the rung is
                    # retried on the next healthy window.
                    log(f"rung {name}: preempted, retry next window")
                    continue
                else:
                    # Rung failed — the window may have closed; re-probe
                    # (with a post-kill breather when the rung was killed
                    # mid-operation) before burning the next rung.
                    if reprobe_after_rung(args.probe_timeout) is None:
                        log("window closed mid-ladder; back to watching")
                        break
            if len(succeeded) == len(rungs):
                log("all rungs captured at least once; resampling mfu at "
                    "idle cadence")
        interval = (args.idle_interval if len(succeeded) == len(rungs)
                    else args.interval)
        # Resample the cheapest rung at idle cadence for a better best-of.
        if len(succeeded) == len(rungs) and dev is not None:
            run_rung(*rungs[0][:2], rungs[0][2], args.artifacts)
        write_stats()  # after the ladder so rung successes are never stale
        time.sleep(max(30, interval))
    write_stats()
    log("deadline reached; watcher exiting")
    return 0


if __name__ == "__main__":
    sys.exit(main())
