#!/usr/bin/env python
"""Curate watcher captures into the committed evidence dir.

Copies the best hardware artifact per rung — selected by the SAME policy the
end-of-round bench applies (``bench._best_artifacts``: ``artifact_ok``,
13h staleness window, img/s model filter, max for throughput/ratio rungs) —
from the live ``.tpu_watch/`` dir into ``docs/evidence/r{N}/`` and rewrites
the "Round N captures" table in ``docs/hardware_results.md`` between the
``<!-- captures:begin -->`` / ``<!-- captures:end -->`` markers.

The live dir is gitignored; the evidence snapshot is what survives into a
fresh checkout (``scaling_projection._resolve_mfu`` reads measured MFU from
it). Run after the watcher logs a capture:

    python tools/sync_evidence.py --round 5
"""

import argparse
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402  (repo-root module; shares the selection policy)

_ROW = {
    "mfu": ("bf16 matmul sustained",
            lambda d: f"**{d['value']} TFLOP/s** "
                      f"({d.get('mfu_vs_peak', '?')} of peak)"
                      + (f", HBM {d['hbm_gbps']} GB/s"
                         if d.get("hbm_gbps") else "")),
    "resnet": ("synthetic training img/s/chip",
               lambda d: f"**{d['value']} img/s** "
                         f"({d.get('vs_baseline', '?')}× the reference's "
                         f"103.6 img/s/GPU)"),
    "lm": ("TransformerLM train tokens/s/chip",
           lambda d: f"**{d['value']} tok/s** (MFU {d.get('mfu', '?')})"),
    "cpe2e": ("control plane: async named path vs in-jit ceiling",
              lambda d: f"**{d['value']}×** on-chip"),
    "trace": ("XLA device trace",
              lambda d: f"captured ({d.get('trace_dir', '?')})"),
    "flash": ("Pallas flash attention vs lax.scan twin",
              lambda d: f"**{d['value']} ms** "
                        f"(speedup {d.get('speedup_vs_scan', '?')}×, "
                        f"equivalent={d.get('equivalent', '?')})"),
}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--round", type=int, required=True)
    p.add_argument("--model", default="resnet50",
                   help="img/s artifacts are curated for this model only "
                        "(same filter as bench.py)")
    p.add_argument("--artifacts", default=os.path.join(REPO, ".tpu_watch"))
    p.add_argument("--doc", default=os.path.join(REPO, "docs",
                                                 "hardware_results.md"))
    p.add_argument("--evidence-dir",
                   default=os.path.join(REPO, "docs", "evidence"),
                   help="parent dir for the r{N} snapshot")
    p.add_argument("--trace-mb-cap", type=float, default=20.0,
                   help="skip snapshotting XLA trace dirs bigger than this")
    args = p.parse_args()

    evidence = os.path.join(args.evidence_dir, f"r{args.round:02d}")
    os.makedirs(evidence, exist_ok=True)
    best = bench._best_artifacts(args.artifacts, args.model)
    for rung, data in best.items():
        src = data.get("_path")
        if not src:
            continue
        dst = os.path.join(evidence, os.path.basename(src))
        if not os.path.exists(dst):
            shutil.copy2(src, dst)
        data["_evidence"] = os.path.relpath(dst, REPO)
        # trace rungs point at an XLA trace DIRECTORY (the offline overlap
        # evidence — reference docs/timeline.rst analog); snapshot it as a
        # tarball when it is reasonably small. The tar is named after the
        # capture's own JSON, so a better later capture (same reused
        # trace_dir) gets its own snapshot instead of being shadowed by
        # the first sync's. Best-effort throughout: the watcher may be
        # rewriting the dir mid-walk, and a failed snapshot must never
        # abort the table rewrite below.
        tdir = data.get("trace_dir")
        if rung in ("trace", "resnet") and tdir and os.path.isdir(tdir):
            tar = os.path.join(
                evidence,
                f"{os.path.splitext(os.path.basename(src))[0]}_trace.tar.gz")
            try:
                tsize = 0
                for r, _, fs in os.walk(tdir):
                    for f in fs:
                        try:
                            tsize += os.path.getsize(os.path.join(r, f))
                        except OSError:
                            pass
                if tsize > args.trace_mb_cap * (1 << 20):
                    print(f"trace dir {tdir} is {tsize / (1 << 20):.1f} MB "
                          f"> cap {args.trace_mb_cap} MB; not snapshotted",
                          file=sys.stderr)
                elif not os.path.exists(tar):
                    import tarfile

                    with tarfile.open(tar, "w:gz") as tf:
                        tf.add(tdir, arcname=os.path.basename(tdir))
            except Exception as e:
                print(f"trace snapshot failed: {e}", file=sys.stderr)
            if os.path.exists(tar):
                data["_trace_evidence"] = os.path.relpath(tar, REPO)

    rows = ["| rung | metric | value | conditions | artifact |",
            "|---|---|---|---|---|"]
    for rung in ("mfu", "resnet", "lm", "cpe2e", "trace", "flash"):
        data = best.get(rung)
        if data is None:
            continue
        label, fmt = _ROW[rung]
        if rung == "resnet":
            label = f"{data.get('metric', args.model).split('_')[0]} {label}"
        cond = (f"{data.get('device_kind', data.get('platform', '?'))}, "
                f"captured {data.get('_captured_at', '?')}")
        cites = f"`{data.get('_evidence', '?')}`"
        if data.get("_trace_evidence"):
            cites += f", `{data['_trace_evidence']}`"
        rows.append(f"| {rung} | {label} | {fmt(data)} | {cond} | "
                    f"{cites} |")
    table = "\n".join(rows)

    with open(args.doc) as f:
        doc = f.read()
    begin, end = "<!-- captures:begin -->", "<!-- captures:end -->"
    if begin not in doc or end not in doc:
        print(f"markers not found in {args.doc}", file=sys.stderr)
        return 1
    head, rest = doc.split(begin, 1)
    _, tail = rest.split(end, 1)
    with open(args.doc, "w") as f:
        f.write(head + begin + "\n" + table + "\n" + end + tail)
    print(f"synced {len(best)} rung(s) -> {evidence}; table updated")
    return 0


if __name__ == "__main__":
    sys.exit(main())
