#!/usr/bin/env python3
"""hvd_slo — live SLO status and BENCH_*.json trend diffing.

Two modes, the consumer side of ISSUE 16's objective plane:

**Live** (default): poll the rank-0 metrics endpoint and render every
declared objective's burn rate / remaining error budget (the
``slo_burn_rate{objective=}`` / ``slo_budget_remaining{objective=}``
gauges), plus per-arm request-latency quantiles from the reqtrace
gauges. ``--once`` exits 2 when any objective is burning (burn >= the
threshold with its fast window full), 0 otherwise — scriptable, like
``grep``.

**Trend** (``--trend A.json B.json [...]``): diff two or more
``BENCH_*.json`` / ``--serving-ab``-style JSON-line files (oldest
first) into a per-metric trend table; a metric that regressed past
``--threshold`` (fractional, direction inferred from its name —
``*_per_sec``/``*tflops``/``*goodput*``/... are higher-is-better)
exits 4. The missing consumer for the bench trajectory: CI can finally
fail on "this PR made transformer_lm slower".

Usage::

    python tools/hvd_slo.py --url http://127.0.0.1:9090
    python tools/hvd_slo.py --once --json
    python tools/hvd_slo.py --trend BENCH_r1.json BENCH_r2.json
    python tools/hvd_slo.py --trend a.json b.json --threshold 0.1 --json

stdlib-only (urllib + json), like every tool in the observability
stack.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from horovod_tpu.observability import regression as _regression  # noqa: E402


def fetch(url: str, timeout: float = 5.0) -> dict:
    """The fleet (or single-process) metrics payload, shaped like
    ``hvd_top``'s: ``{"metrics": {name: {"samples": {...}}}}``."""
    try:
        with urllib.request.urlopen(
                f"{url}/fleet.json", timeout=timeout) as r:
            return json.load(r)
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise
    with urllib.request.urlopen(
            f"{url}/metrics.json", timeout=timeout) as r:
        snap = json.load(r)
    metrics = {}
    for name, fam in snap.items():
        samples = {}
        for key, sample in fam.get("samples", {}).items():
            if fam["type"] == "histogram":
                samples[key] = sample
            else:
                v = float(sample)
                samples[key] = {"min": v, "mean": v, "max": v}
        metrics[name] = {"type": fam["type"], "samples": samples}
    return {"metrics": metrics}


def _labeled_max(metrics: dict, name: str) -> dict:
    """{label-key: max-across-ranks value} for a labeled gauge family."""
    fam = metrics.get(name) or {}
    out = {}
    for key, s in fam.get("samples", {}).items():
        v = s.get("max")
        if v is None:
            v = s.get("mean")
        if v is not None:
            out[key] = float(v)
    return out


def _label(key: str, name: str) -> str:
    labels = dict(
        item.partition("=")[::2] for item in key.split(",") if item)
    return labels.get(name, key)


def slo_table(metrics: dict) -> list:
    """Per-objective rows from the live gauges (empty when no SLO
    registry is publishing)."""
    burn = _labeled_max(metrics, "slo_burn_rate")
    remaining = _labeled_max(metrics, "slo_budget_remaining")
    rows = []
    for key in sorted(set(burn) | set(remaining)):
        b = burn.get(key)
        rows.append({
            "objective": _label(key, "objective"),
            "burn_rate": b,
            "budget_remaining": remaining.get(key),
            "burning": b is not None and (b >= 1.0 or b < 0),
        })
    return rows


def latency_rows(metrics: dict) -> list:
    """Per-arm TTFT/TPOT p50/p99 from the reqtrace gauges."""
    arms = {}
    for fam, field in (
        ("reqtrace_ttft_p50", "ttft_p50"),
        ("reqtrace_ttft_p99", "ttft_p99"),
        ("reqtrace_tpot_p50", "tpot_p50"),
        ("reqtrace_tpot_p99", "tpot_p99"),
    ):
        for key, v in _labeled_max(metrics, fam).items():
            arms.setdefault(_label(key, "arm"), {})[field] = v
    return [dict(arm=a, **vals) for a, vals in sorted(arms.items())]


def _fmt(v, unit: str = "") -> str:
    if v is None:
        return "-"
    return f"{v:.4g}{unit}"


def render_live(payload: dict) -> str:
    metrics = payload.get("metrics", {})
    lines = [f"hvd_slo — {time.strftime('%H:%M:%S')}"]
    rows = slo_table(metrics)
    if not rows:
        lines.append("no SLO objectives declared (set HOROVOD_SLO)")
    else:
        lines.append(
            f"{'OBJECTIVE':<24} {'BURN':>8} {'BUDGET LEFT':>12}  STATE")
        worst = None
        for r in rows:
            state = "BURNING" if r["burning"] else "ok"
            lines.append(
                f"{r['objective']:<24} {_fmt(r['burn_rate'], 'x'):>8} "
                f"{_fmt(r['budget_remaining']):>12}  {state}")
            b = r["burn_rate"]
            if b is not None and b < 0:
                b = float("inf")  # zero-budget objective violated
            if b is not None and (worst is None or b > worst[1]):
                worst = (r["objective"], b)
        if worst is not None:
            lines.append(f"worst offender: {worst[0]}")
    lat = latency_rows(metrics)
    if lat:
        lines.append("")
        lines.append("request latency (windowed, seconds):")
        for r in lat:
            lines.append(
                f"  arm={r['arm']}: ttft p50/p99 "
                f"{_fmt(r.get('ttft_p50'))}/{_fmt(r.get('ttft_p99'))}, "
                f"tpot p50/p99 "
                f"{_fmt(r.get('tpot_p50'))}/{_fmt(r.get('tpot_p99'))}")
    return "\n".join(lines)


def render_trend(result: dict) -> str:
    lines = [
        f"{'METRIC':<46} {'BASELINE':>12} {'LAST':>12} "
        f"{'DELTA':>8}  VERDICT"
    ]
    for r in result["rows"]:
        arrow = "+" if r["delta_frac"] >= 0 else ""
        verdict = "REGRESSED" if r["regressed"] else "ok"
        name = r["metric"]
        if len(name) > 46:
            name = name[:43] + "..."
        lines.append(
            f"{name:<46} {_fmt(r['baseline']):>12} {_fmt(r['last']):>12} "
            f"{arrow}{r['delta_frac'] * 100:.1f}%  {verdict}")
    n = len(result["regressed"])
    lines.append(
        f"{n} metric(s) regressed past "
        f"{result['threshold'] * 100:g}%"
        + (f": {', '.join(result['regressed'])}" if n else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default="http://127.0.0.1:9090",
                   help="rank-0 metrics endpoint (HOROVOD_METRICS_PORT)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="live-mode refresh cadence in seconds")
    p.add_argument("--once", action="store_true",
                   help="one frame, exit 2 if any objective is burning")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output instead of the table")
    p.add_argument("--trend", nargs="+", metavar="BENCH_JSON",
                   help="diff >= 2 bench JSON files (oldest first); "
                        "exit 4 on regression past --threshold")
    p.add_argument("--threshold", type=float, default=0.05,
                   help="fractional regression threshold for --trend")
    args = p.parse_args(argv)

    if args.trend:
        if len(args.trend) < 2:
            print("hvd_slo: --trend needs >= 2 bench files",
                  file=sys.stderr)
            return 1
        try:
            series = [_regression.load_bench(f) for f in args.trend]
        except OSError as e:
            print(f"hvd_slo: cannot read bench file: {e}",
                  file=sys.stderr)
            return 1
        result = _regression.trend(series, threshold=args.threshold)
        result["files"] = list(args.trend)
        if args.json:
            print(json.dumps(result, indent=1))
        else:
            print(render_trend(result))
        return 4 if result["regressed"] else 0

    while True:
        try:
            payload = fetch(args.url)
        except (OSError, urllib.error.URLError, ValueError) as e:
            print(f"hvd_slo: cannot scrape {args.url}: {e}",
                  file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        if args.json:
            print(json.dumps({
                "objectives": slo_table(payload.get("metrics", {})),
                "latency": latency_rows(payload.get("metrics", {})),
            }, indent=1))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")
            print(render_live(payload))
        if args.once:
            burning = any(
                r["burning"]
                for r in slo_table(payload.get("metrics", {})))
            return 2 if burning else 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
