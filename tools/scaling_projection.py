#!/usr/bin/env python
"""Analytic DP scaling projection from the compiled step's HLO.

VERDICT r3 weakness: the virtual CPU mesh gives no scaling-efficiency signal
of any kind (all 8 "devices" share host cores). This tool produces the
*relative* signal the hardware cannot: it compiles the real DP train step,
extracts per-step communication bytes (all-reduce HLO ops) and FLOPs from
the compiled program, and projects scaling efficiency with the standard
ring-allreduce roofline (the scaling-book recipe):

    t_compute = flops / peak_flops
    t_comm    = 2 * (n-1)/n * comm_bytes / ici_bandwidth
    efficiency(n) = t_compute / max(t_compute, t_comm)   # full overlap
    efficiency_no_overlap(n) = t_compute / (t_compute + t_comm)

The reference's published table (docs/benchmarks.rst:10-14: 90% standard,
68% VGG-16 on 25GbE) is exactly this tradeoff measured on hardware; this
projection reproduces its *shape* (VGG's fat dense layers push comm_bytes/
flops up) from the compiled program alone.

Run: python tools/scaling_projection.py [--model resnet50 --chips 8 32 256]
Emits one JSON line.
"""

import argparse
import json
import math
import os
import re
import sys
from typing import Optional

import numpy as np

# self-sufficient from any cwd: `python tools/scaling_projection.py` puts
# tools/ (not the repo root) on sys.path[0]
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


# per-chip peak numbers (public figures); the projection is a ratio, so only
# the peak_flops/ici_bw quotient matters materially
_HW = {
    # TPU v4: 275 TFLOP/s bf16, 3D torus, ~300 GB/s aggregate ICI per chip
    "tpu-v4": {"peak_flops": 275e12, "ici_bw": 300e9},
    # TPU v5e: 197 TFLOP/s bf16, ~160 GB/s
    "tpu-v5e": {"peak_flops": 197e12, "ici_bw": 160e9},
    # the reference's own benchmark fabric: P100 (10.6 TFLOP/s fp32) + 25GbE
    "p100-25gbe": {"peak_flops": 10.6e12, "ici_bw": 3.125e9},
}

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "s8": 1, "u8": 1, "pred": 1, "s64": 8, "u64": 8}


_COMM_OPS = (
    "all-reduce", "reduce-scatter", "all-gather", "collective-permute",
    "all-to-all",
)


def _shape_bytes(dt: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dt, 4)


def comm_ops_from_hlo(hlo_text: str):
    """Extract ``(op, output_bytes, group_size)`` for every collective.

    Async ``-start`` ops return ``(operand, result, ...)`` tuples — only the
    LARGEST array element (the result; equal to the operand for permute/AR)
    is counted, and the ``-done`` twin is skipped entirely. ``group_size``
    comes from ``replica_groups``: explicit ``{{0,1},{2,3}}`` lists or the
    iota form ``[G,S]<=[N]`` (size = S); 0 means "unknown/all"."""
    out = []
    pat = (r"=\s*((?:\(.*?\))|(?:\S+))\s+(%s)(-start)?(?!-done)\(([^\n]*)"
           % "|".join(_COMM_OPS))
    for m in re.finditer(pat, hlo_text):
        shapes, op, is_start, rest = m.groups()
        elems = [_shape_bytes(dt, dims)
                 for dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", shapes)]
        if not elems:
            continue
        nbytes = max(elems) if is_start else sum(elems)
        g = 0
        gm = re.search(r"replica_groups=\{\{([\d,]+)\}", rest)
        if gm:
            g = len(gm.group(1).split(","))
        else:
            gm = re.search(r"replica_groups=\[\d+,(\d+)\]<=", rest)
            if gm:
                g = int(gm.group(1))
        out.append((op, nbytes, g))
    return out


def comm_bytes_from_hlo(hlo_text: str) -> int:
    """Total collective output bytes (see :func:`comm_ops_from_hlo`)."""
    return sum(b for _, b, _ in comm_ops_from_hlo(hlo_text))


def zero1_sync_bytes(grad_bytes: float, n: int, *, wire_bytes: float = None,
                     update_bytes: float = None) -> dict:
    """Ring byte model for the DP gradient exchange, allreduce vs the ZeRO-1
    reduce-scatter -> all-gather decomposition
    (``DistributedOptimizer(shard_optimizer=True)``):

    - allreduce moves ``2(N-1)/N·B`` gradient bytes per step;
    - sharded moves ``(N-1)/N·B`` gradient bytes (the reduce-scatter — half)
      plus ``(N-1)/N·P`` parameter-update bytes (the all-gather).

    With ``wire_bytes`` (compressed gradient volume, e.g. bf16 = B/2) the
    asymmetry shows up: the RS leg rides the wire dtype while the AG leg
    carries full-precision updates — sharded+fp16 moves
    ``(N-1)/N·(B/2 + P)`` vs allreduce+fp16's ``2(N-1)/N·B/2``. These are
    the numbers ``grad_sync_bytes_per_step`` / ``param_gather_bytes_per_step``
    report from the live step (``horovod_tpu.optim._record_sync_bytes``)."""
    w = grad_bytes if wire_bytes is None else wire_bytes
    u = grad_bytes if update_bytes is None else update_bytes
    ring = (n - 1) / n if n > 1 else 0.0
    return {
        "allreduce": 2.0 * ring * w,
        "rs": ring * w,
        "ag": ring * u,
        "sharded_total": ring * (w + u),
    }


def overlap_step_time(compute_s: float, comm_s: float, n_buckets: int, *,
                      latency_s: float = 0.0) -> dict:
    """Analytic step-time model for bucketed backward-pass gradient sync
    (``DistributedOptimizer(overlap=True)`` /
    ``make_shardmap_train_step(overlap=True)``).

    Monolithic sync serializes: ``t = compute + comm`` (the collective's
    input is the whole gradient tree, ready only when backprop ends).
    With K reverse-emission buckets each collective depends only on its
    own leaves' cotangents, so comm rides under the remaining backward:

        overlapped = max(compute, comm) + min(compute, comm)/K
                     + K * latency_s

    The exposed ``min/K`` term is the non-overlappable boundary: the
    FIRST bucket's collective cannot start before ~1/K of the backward
    has produced its leaves, and the LAST bucket's transfer has no
    compute left to hide behind — one bucket's worth of the smaller term
    always pokes out. ``latency_s`` charges per-collective launch
    overhead (K small fixed costs — why shrinking buckets below ~MBs
    loses). Clamped at the serial time: overlap never makes a step
    slower in this model. This is the same tradeoff curve as PyTorch
    DDP's bucket_cap_mb (Li et al., VLDB 2020 §4.2) and the reference's
    64 MB fusion buffer.
    """
    compute_s = float(compute_s)
    comm_s = float(comm_s)
    k = max(1, int(n_buckets))
    serial = compute_s + comm_s
    if k == 1:
        overlapped = serial
    else:
        overlapped = min(
            serial,
            max(compute_s, comm_s) + min(compute_s, comm_s) / k
            + k * float(latency_s),
        )
    return {
        "serial_s": serial,
        "overlapped_s": overlapped,
        "speedup": (serial / overlapped) if overlapped > 0 else 1.0,
        "bound": "comm" if comm_s > compute_s else "compute",
        "n_buckets": k,
    }


def input_step_time(compute_s: float, load_s: float, prefetch: int) -> dict:
    """Analytic step-time model for host-side input prefetch
    (:class:`horovod_tpu.data.ResumableLoader`; ``bench.py --input-ab``).

    With ``prefetch=0`` the host gather serializes with the step:
    ``t = compute + load``. With any prefetch depth the producer thread
    overlaps batch ``i+1``'s gather with step ``i``'s compute, so the
    steady-state step time is ``max(compute, load)`` — depth beyond 1
    only absorbs load *variance*, it cannot beat the max() floor (the
    pipeline is a two-stage queue; Little's law, not magic). A pipeline
    with ``load > compute`` is **input-bound**: the ratio stays above 1
    but the step time is the disk's, which is exactly the state the
    ``data_wait_seconds`` metric and input-side straggler attribution
    exist to name (docs/data.md).
    """
    compute_s = float(compute_s)
    load_s = float(load_s)
    serial = compute_s + load_s
    overlapped = serial if int(prefetch) < 1 else max(compute_s, load_s)
    return {
        "serial_s": serial,
        "overlapped_s": overlapped,
        "speedup": (serial / overlapped) if overlapped > 0 else 1.0,
        "bound": "input" if load_s > compute_s else "compute",
        "prefetch": int(prefetch),
    }


def _as_shapes(shapes):
    """Normalize the byte-model input: an int is one flat leaf, a single
    shape tuple is one leaf, else an iterable of shape tuples."""
    if isinstance(shapes, (int, np.integer)):
        return [(int(shapes),)]
    shapes = list(shapes)
    if shapes and isinstance(shapes[0], int):
        return [tuple(shapes)]
    return [tuple(s) for s in shapes]


def _int8_leaf_bytes(size: int, block: int, scale_bytes: int,
                     itemsize: int, min_elems: int) -> int:
    if size < min_elems:  # below the quantize floor: rides uncompressed
        return size * itemsize
    return size + -(-size // block) * scale_bytes


def int8_sync_bytes(shapes, n: int, *, block: int = 256,
                    scale_bytes: int = 2, itemsize: int = 4,
                    min_elems: int = 1024) -> dict:
    """Ring byte model for blockwise int8 gradient compression
    (``Compression.int8``): each float leaf costs ``size * 1`` int8 bytes
    plus ``ceil(size / block) * scale_bytes`` bf16 scales per wire
    direction; leaves below ``min_elems`` (the compressor's
    ``min_quant_elems`` floor — the ring's per-chunk block padding would
    cost more than fp32 there) ride uncompressed at ``itemsize``. This is
    the same per-leaf pricing the live step's ``Compressor.wire_bytes``
    hook reports into ``grad_sync_bytes_per_step``. ``shapes`` is an int
    (one flat leaf), a shape tuple, or a list of shape tuples (per-leaf
    ceil matters)."""
    shapes = _as_shapes(shapes)
    elems = sum(int(np.prod(s, dtype=np.int64)) for s in shapes)
    wire = sum(
        _int8_leaf_bytes(int(np.prod(s, dtype=np.int64)), block,
                         scale_bytes, itemsize, min_elems)
        for s in shapes
    )
    dense = elems * itemsize
    ring = (n - 1) / n if n > 1 else 0.0
    return {
        "allreduce": 2.0 * ring * wire,
        "rs": ring * wire,
        "fp32_allreduce": 2.0 * ring * dense,
        "wire_bytes": wire,
        "ratio_vs_fp32": wire / dense if dense else 0.0,
    }


def fsdp_gather_wire_bytes(shapes, n: int, *, wire: str = "none",
                           block: int = 256, scale_bytes: int = 2,
                           itemsize: int = 4,
                           min_elems: int = 1024) -> int:
    """Wire image of ONE ZeRO-3 parameter all-gather over a single packed
    group (one dtype, no bucket splitting — price a bucketed plan by
    calling this once per bucket). The flat pack pads the group to a
    multiple of N (``Lp = L + (-L) % N``); the fp wire moves ``Lp *
    itemsize``. The int8 wire quantizes each rank's shard blockwise
    before the gather, so every rank's block-padded shard travels as int8
    plus one bf16 scale per block, times N ranks; groups under the
    ``min_elems`` quantize floor ride uncompressed. Analytic twin of
    ``horovod_tpu.optim._fsdp_gather_wire_bytes`` — a test pins them
    equal against the live ``param_gather_bytes_per_step`` gauge."""
    shapes = _as_shapes(shapes)
    size = sum(int(np.prod(s, dtype=np.int64)) for s in shapes)
    lp = size + (-size) % n
    if wire == "int8" and lp >= min_elems:
        s = lp // n
        sp = s + (-s) % block
        return n * (sp + (sp // block) * scale_bytes)
    return lp * itemsize


def zero3_sync_bytes(shapes, n: int, *, wire: str = "none",
                     gathers_per_step: int = 2, block: int = 256,
                     scale_bytes: int = 2, itemsize: int = 4,
                     min_elems: int = 1024) -> dict:
    """Ring byte model for ZeRO-3 gather-on-use
    (``DistributedOptimizer(shard_params=True)`` /
    ``make_shardmap_train_step(shard_params=True)``):

    - the parameter all-gather moves ``(N-1)/N · G`` bytes and runs
      **twice** per step (forward gather-on-use, then the
      ``jax.checkpoint`` re-gather in backward — rematerialization trades
      a second gather for not holding the full params live);
    - gradients reduce-scatter once at ``(N-1)/N · B`` in full precision
      (the int8 knob compresses only the gather leg — the gradient leg
      stays exact, which is what keeps the fp32 trajectory bit-identical
      to ZeRO-1).

    ``zero1_total`` is the same model's ZeRO-1 cost (RS + AG of the same
    parameter volume, once each) — ZeRO-3 loses on pure wire bytes
    whenever ``gathers_per_step · G_wire > G``: with the fp32 wire that
    is always (3 legs vs 2); the int8 wire breaks even near G_wire ≈ G/2
    and wins below. What ZeRO-3 buys instead is **memory** — params live
    ``1/N``-sharded between uses. These are the numbers the live
    ``grad_sync_bytes_per_step{mode="zero3"}`` /
    ``param_gather_bytes_per_step{mode="zero3"}`` gauges report
    (``horovod_tpu.optim._fsdp_update``)."""
    shapes = _as_shapes(shapes)
    size = sum(int(np.prod(s, dtype=np.int64)) for s in shapes)
    lp = size + (-size) % n
    gw = fsdp_gather_wire_bytes(
        shapes, n, wire=wire, block=block, scale_bytes=scale_bytes,
        itemsize=itemsize, min_elems=min_elems)
    rw = lp * itemsize  # gradient leg: always full precision
    ring = (n - 1) / n if n > 1 else 0.0
    return {
        "param_gather": ring * gathers_per_step * gw,
        "grad_reduce_scatter": ring * rw,
        "zero3_total": ring * (gathers_per_step * gw + rw),
        "zero1_total": 2.0 * ring * lp * itemsize,
        "gather_wire_bytes": gw,
    }


def powersgd_sync_bytes(shapes, rank: int, n: int, *, block: int = 256,
                        scale_bytes: int = 2, itemsize: int = 4,
                        min_elems: int = 1024) -> dict:
    """Ring byte model for PowerSGD rank-``r`` compression
    (``Compression.powersgd(rank)``): a >=2-D leaf ``[d0, *rest]`` syncs
    ``(d0 + prod(rest)) * min(rank, d0, prod(rest))`` f32 factor elements
    (P + Q, each a full ring allreduce — hence the 2(N−1)/N factor on the
    whole sum); 1-D leaves ride the int8 fallback (dense below its
    ``min_elems`` floor). Mirrors the live ``wire_bytes`` hook exactly, so
    the model == the gauge."""
    shapes = _as_shapes(shapes)
    factor = 0
    fallback = 0
    dense = 0
    for s in shapes:
        size = int(np.prod(s, dtype=np.int64))
        dense += size * itemsize
        d0 = int(s[0]) if len(s) >= 2 else 0
        m = int(np.prod(s[1:], dtype=np.int64)) if len(s) >= 2 else 0
        r = min(rank, d0, m)
        # factorize only when the factors beat the dense leaf (the live
        # compressor's crossover rule); else the int8/dense fallback
        if len(s) >= 2 and (d0 + m) * r < d0 * m:
            factor += (d0 + m) * r * itemsize
        else:
            fallback += _int8_leaf_bytes(size, block, scale_bytes,
                                         itemsize, min_elems)
    wire = factor + fallback
    ring = (n - 1) / n if n > 1 else 0.0
    return {
        "allreduce": 2.0 * ring * wire,
        "factor_bytes": factor,
        "int8_fallback_bytes": fallback,
        "fp32_allreduce": 2.0 * ring * dense,
        "wire_bytes": wire,
        "ratio_vs_fp32": wire / dense if dense else 0.0,
    }


def pallas_hot_path_bytes(shapes, n: int, *, block: int = 256,
                          scale_bytes: int = 2, itemsize: int = 4,
                          error_feedback: bool = True,
                          epilogue: str = "scatter") -> dict:
    """Analytic HBM-traffic model of the int8 wire hot path, discrete HLO
    vs the fused Pallas kernels (``HOROVOD_PALLAS``), for one flat packed
    gradient buffer of ``E`` f32 elements exchanged over ``n`` ranks.
    Wire (ICI/DCN) bytes are identical by construction — Pallas replaces
    elementwise HLO, never collectives — so this model counts only the
    HBM round-trips *between* the collectives:

    discrete (``q`` = ``E + ceil(E/block)*scale_bytes`` wire-image bytes):

    - EF roundtrip (when ``error_feedback``): the separate
      ``quantize_roundtrip_chunked`` pass — read 4E, write q, read q,
      write 4E;
    - quantize for the wire: read 4E, write q (the corrected buffer is
      read a SECOND time);
    - dequantize: read q, write 4E (the ``[N, sp]`` f32 matrix
      materialized post-``all_to_all``);
    - accumulate: read 4E, write 4E/n;
    - requantize (``epilogue="allreduce"`` only): read 4E/n, write q/n;
    - Adam on the shard (S = E/n): the optax chain's mu/nu/mu_hat/nu_hat
      /prescale/update materializations — 56·4·S/4 bytes un-fused. XLA's
      elementwise fusion recovers much of this stage in practice; the
      model bounds the win (the same honesty note as
      :func:`overlap_step_time`'s launch-latency term).

    fused:

    - quantize kernel: read 4E, write q (+ write 4E roundtrip when EF —
      ONE pass serves the wire and the residual);
    - dequant-accumulate(-requantize) kernel: read q, write 4E/n
      (scatter) or q/n (allreduce) — no f32 matrix, no shard round-trip;
    - fused Adam kernel: read 12S, write 12S.
    """
    if epilogue not in ("scatter", "allreduce"):
        raise ValueError(f"epilogue must be scatter|allreduce, got "
                         f"{epilogue!r}")
    shapes = _as_shapes(shapes)
    e = sum(int(np.prod(s, dtype=np.int64)) for s in shapes)
    f = e * itemsize                                # f32 buffer bytes
    q = e + -(-e // block) * scale_bytes            # wire-image bytes
    s_bytes = f / max(n, 1)                         # one shard, f32
    discrete = {
        "quantize": f + q,
        "dequantize": q + f,
        "accumulate": f + s_bytes,
        "adam_shard": 56 * s_bytes / 4,
    }
    fused = {
        "quantize": f + q,
        "dequant_accumulate": q + s_bytes,
        "adam_shard": 24 * s_bytes / 4,
    }
    if error_feedback:
        discrete["ef_roundtrip"] = 2 * f + 2 * q
        fused["quantize"] += f                      # the fused rt write
    if epilogue == "allreduce":
        discrete["requantize"] = s_bytes + q / n
        fused["dequant_accumulate"] = q + q / n
    d_total = sum(discrete.values())
    f_total = sum(fused.values())
    return {
        "elems": e,
        "n": n,
        "wire_bytes": q,
        "discrete": discrete,
        "fused": fused,
        "discrete_bytes": d_total,
        "fused_bytes": f_total,
        "savings_ratio": (d_total - f_total) / d_total if d_total else 0.0,
    }


def publish_bytes(shapes, *, keyframe_every: int = 8, block: int = 256,
                  scale_bytes: int = 2, itemsize: int = 4,
                  min_elems: int = 1024) -> dict:
    """Byte model for streaming weight publication
    (:mod:`horovod_tpu.serving`): a keyframe moves every leaf raw at
    ``itemsize``; a delta moves each quantizable leaf as blockwise int8
    (padded to whole blocks — the serving encoder quantizes the
    block-padded flat vector, so the pad bytes ARE on the wire) plus bf16
    scales, with sub-floor leaves riding their raw delta. Mirrors the live
    ``serving_publish_wire_bytes`` gauge exactly (model == gauge), and
    amortizes one keyframe per ``keyframe_every`` generations against the
    full-checkpoint bytes (``checkpoint.state_nbytes``) the handoff would
    otherwise pay per refresh."""
    shapes = _as_shapes(shapes)
    key = 0
    delta = 0
    for s in shapes:
        size = int(np.prod(s, dtype=np.int64))
        key += size * itemsize
        if size >= min_elems:
            padded = -(-size // block) * block
            delta += padded + (padded // block) * scale_bytes
        else:
            delta += size * itemsize
    amortized = (key + (keyframe_every - 1) * delta) / keyframe_every
    return {
        "keyframe_bytes": key,
        "delta_bytes": delta,
        "checkpoint_bytes": key,
        "amortized_bytes_per_generation": amortized,
        "delta_ratio_vs_checkpoint": delta / key if key else 0.0,
        "amortized_ratio_vs_checkpoint": amortized / key if key else 0.0,
        "keyframe_every": keyframe_every,
    }


def serving_goodput(prompt_lens, max_new: int, *, max_batch: int,
                    prefill_chunk: int = 16) -> dict:
    """Analytic goodput model for the serving engine's continuous batching
    vs static batched ``generate()`` (``bench.py --serving-ab``).

    The unit is the **slot-token**: one batch row occupied for one model
    invocation position. Static batching right-pads every prompt to the
    longest and holds every row until the whole batch finishes, so a batch
    of B rows pays ``B × (max(L) + max_new)`` slot-tokens per wave (and
    waves of B when there are more requests than rows). Continuous
    batching pays each sequence only its own keep — prompt rounded up to
    whole prefill chunks plus its decode steps — because a finished row's
    slot is re-admitted at the same iteration boundary.

    ``goodput_ratio`` is useful-tokens-per-slot-token of the continuous
    engine over the static arm — the *scheduling* win with compute held
    equal. It exceeds 1 exactly when prompts are ragged or the request
    count doesn't divide the batch; on a uniform, batch-aligned workload
    it is 1.0 by construction. The CPU-measured ratio in the A/B rung sits
    below this model: the engine pays per-iteration host scheduling and a
    page-table gather that a real accelerator overlaps."""
    lens = [int(x) for x in np.asarray(prompt_lens).reshape(-1)]
    if not lens:
        raise ValueError("prompt_lens must be non-empty")
    b = int(max_batch)
    useful = sum(lens) + len(lens) * int(max_new)
    # static: ceil(R / B) waves, every slot in a wave pays the wave's
    # padded length (empty slots in the last wave still step)
    waves = [lens[i:i + b] for i in range(0, len(lens), b)]
    static_cost = sum(
        b * (max(w) + int(max_new)) for w in waves
    )
    # continuous: each sequence pays its chunk-rounded prompt + decode
    chunk = max(1, int(prefill_chunk))
    cont_cost = sum(
        -(-l // chunk) * chunk + int(max_new) for l in lens
    )
    static_util = useful / static_cost if static_cost else 0.0
    cont_util = useful / cont_cost if cont_cost else 0.0
    return {
        "useful_tokens": useful,
        "static_slot_tokens": static_cost,
        "continuous_slot_tokens": cont_cost,
        "static_utilization": static_util,
        "continuous_utilization": cont_util,
        "goodput_ratio": (cont_util / static_util) if static_util else 0.0,
        "max_batch": b,
        "prefill_chunk": chunk,
    }


def prefix_prefill_flops(prompt_lens, cached_lens, *, page_size: int,
                         prefill_chunk: int,
                         params_per_token: Optional[int] = None) -> dict:
    """Analytic prefill-savings model for the serving prefix cache
    (``bench.py --prefix-ab``).

    Mirrors the engine's hit rules EXACTLY, so the measured
    ``serving_prefill_tokens`` delta on a deterministic workload pins to
    this model token-for-token:

    - a hit only aliases whole pages whose content chain is resident,
      up to ``cached_lens[i]`` shared-prefix tokens;
    - the hit rounds down to a multiple of
      ``lcm(page_size, prefill_chunk)`` — chunk starts must stay
      multiples of ``prefill_chunk`` or a clamped pad tail could fold
      into a real page;
    - the hit stays strictly below the prompt end: the final prompt
      token always prefills (it produces the first-token logits).

    ``prefill_token_ratio`` is cold/cached prefill tokens (≥ 1; the
    FLOP saving at ``2 · params · tokens`` per dense forward when
    `params_per_token` is given)."""
    lens = [int(x) for x in np.asarray(prompt_lens).reshape(-1)]
    shared = [int(x) for x in np.asarray(cached_lens).reshape(-1)]
    if len(lens) != len(shared):
        raise ValueError(
            f"prompt_lens and cached_lens length mismatch: "
            f"{len(lens)} vs {len(shared)}")
    ps, chunk = int(page_size), max(1, int(prefill_chunk))
    align = ps * chunk // math.gcd(ps, chunk)
    hits = []
    for l, c in zip(lens, shared):
        resident = min(c, l) // ps            # whole resident blocks
        cap = (l - 1) // align * (align // ps)  # aligned, < prompt end
        n = min(resident, cap)
        n -= n % (align // ps)
        hits.append(n * ps)
    cold = sum(lens)
    cached = sum(l - h for l, h in zip(lens, hits))
    out = {
        "cold_prefill_tokens": cold,
        "cached_prefill_tokens": cached,
        "saved_tokens": cold - cached,
        "hit_tokens_per_request": hits,
        "prefill_token_ratio": cold / cached if cached else float("inf"),
        "page_size": ps,
        "prefill_chunk": chunk,
        "alignment_tokens": align,
    }
    if params_per_token is not None:
        out["cold_prefill_flops"] = 2 * int(params_per_token) * cold
        out["cached_prefill_flops"] = 2 * int(params_per_token) * cached
    return out


def spec_decode_tokens(max_new: int, lookahead: int, *,
                       acceptance_rate: float = 1.0,
                       draft_cost: float = 0.0,
                       n_requests: int = 1) -> dict:
    """Analytic token-accounting model for speculative decoding
    (``bench.py --spec-ab``).

    The engine's schedule per request: the first token comes from the
    prefill forward; the remaining ``max_new − 1`` decode while the
    budget allows a full iteration — a speculative iteration needs
    ``K + 1`` tokens of headroom (K drafts + the verify's bonus token)
    and emits all ``K + 1`` under full acceptance, anything shorter
    falls back to one plain decode per token. At
    ``acceptance_rate == 1`` (the deterministic A/B arm runs the draft
    at the target's full depth, so draft argmax ≡ target argmax) the
    counts are exact integers the ``spec_proposed`` / ``spec_accepted``
    counters must match; for partial acceptance the expectation
    ``E[tokens/iteration] = sum_{i=0..K} α^i`` (per-token iid α) scales
    the decode-pass saving.

    ``decode_goodput_ratio`` is plain target passes over spec-mode
    target passes plus `draft_cost`-weighted draft passes (draft FLOPs
    as a fraction of a target pass, e.g. ``draft_depth / depth``)."""
    K = int(lookahead)
    if K < 1:
        raise ValueError(f"lookahead must be >= 1, got {lookahead}")
    a = float(acceptance_rate)
    decode = max(0, int(max_new) - 1)
    spec_iters = decode // (K + 1)
    plain = decode - spec_iters * (K + 1)
    R = int(n_requests)
    exp_per_iter = sum(a ** i for i in range(K + 1))
    out = {
        "max_new": int(max_new),
        "lookahead": K,
        "acceptance_rate": a,
        "spec_iterations": spec_iters * R,
        "plain_decodes": plain * R,
        "proposed": spec_iters * K * R,
        "accepted": int(spec_iters * K * R) if a >= 1.0
        else spec_iters * R * (exp_per_iter - 1.0),
        "expected_tokens_per_iteration": exp_per_iter,
        "target_passes_plain": decode * R,
        "target_passes_spec": (spec_iters + plain) * R,
        # K proposal forwards + 1 KV-backfill forward per iteration (the
        # engine writes d_K's draft KV so a fully-accepted round leaves
        # no hole behind the next frontier)
        "draft_passes": spec_iters * (K + 1) * R,
    }
    cost = ((spec_iters + plain)
            + float(draft_cost) * spec_iters * (K + 1))
    out["decode_goodput_ratio"] = decode / cost if cost else 1.0
    return out


def comm_time_s(ops, ici_bw: float, default_group: int) -> float:
    """Wire time under standard ring algorithms per op type:
    all-reduce 2(g-1)/g · B; all-gather/all-to-all (g-1)/g · B (B = output);
    reduce-scatter (g-1) · B (output is the 1/g shard); permute B."""
    t = 0.0
    for op, b, g in ops:
        g = g or default_group
        if op == "all-reduce":
            t += 2.0 * (g - 1) / g * b / ici_bw
        elif op in ("all-gather", "all-to-all"):
            t += (g - 1) / g * b / ici_bw
        elif op == "reduce-scatter":
            t += (g - 1) * b / ici_bw
        else:  # collective-permute: each device ships its block once
            t += b / ici_bw
    return t


def _lm_comm_fraction(args) -> int:
    """SP (ring attention) / TP comm-fraction from the compiled LM step.

    Long-context/SP has no reference counterpart (SURVEY.md §5.7); the
    signal here is the comm:compute split of the actual compiled program at
    the compiled mesh — ppermute bytes for the ring, per-block allreduce
    bytes for TP — against the hardware roofline."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import TransformerLM, transformer_param_specs
    from horovod_tpu.parallel import ring_attention
    from horovod_tpu.training import (
        init_model, make_jit_train_step, make_sp_train_step, replicate,
        token_xent,
    )

    hvd.shutdown()
    inner_axis = "seq" if args.parallelism == "sp" else "model"
    axes = {"data": 2, inner_axis: 4}
    hvd.init(axes=axes)
    mesh = hvd.mesh()
    tx = optax.sgd(0.1)
    kw = dict(vocab=args.vocab, dim=args.dim, depth=args.depth,
              heads=args.heads, max_len=args.seq_len)

    if args.parallelism == "sp":
        model = TransformerLM(
            attention_fn=functools.partial(
                ring_attention, axis_name="seq", causal=True),
            **kw,
        )
        # params are attention-fn-independent: init a plain twin (ring
        # attention needs the bound 'seq' axis the step's shard_map provides)
        sample = jnp.zeros((1, args.seq_len // axes["seq"]), jnp.int32)
        params, _ = init_model(TransformerLM(**kw), jax.random.PRNGKey(0),
                               sample)
        step = make_sp_train_step(model, tx, donate=False)
        toks = jax.device_put(
            jnp.zeros((2, args.seq_len), jnp.int32),
            NamedSharding(mesh, P("data", "seq")))
        lowered = step.lower(replicate(params), replicate(tx.init(params)),
                             toks, toks)
    else:
        model = TransformerLM(**kw)
        sample = jnp.zeros((1, args.seq_len), jnp.int32)
        params, batch_stats = init_model(model, jax.random.PRNGKey(0), sample)
        specs = transformer_param_specs(params, model_axis="model")
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs)
        opt_state = tx.init(params)
        toks = jax.device_put(
            jnp.zeros((2, args.seq_len), jnp.int32),
            NamedSharding(mesh, P("data")))
        # the stock jit step (same loss the SP step uses; XLA inserts the
        # TP psums from the param shardings)
        step = make_jit_train_step(model, tx, loss_fn=token_xent,
                                   donate=False)
        lowered = step.lower(params, batch_stats, opt_state, toks, toks)

    _report_comm_fraction(
        args, lowered.compile(), mesh,
        default_group=axes[inner_axis],
        extra={"seq_len": args.seq_len, "dim": args.dim,
               "depth": args.depth},
    )
    hvd.shutdown()
    return 0


def _report_comm_fraction(args, compiled, mesh, *, default_group: int,
                          extra: dict) -> None:
    """Shared tail of the sp/tp/ep modes: collective extraction, roofline
    (ring-algorithm wire time per op, group sizes parsed from the HLO —
    the same cost model the dp projection applies), one JSON line."""
    comm_ops = comm_ops_from_hlo(compiled.as_text())
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    flops_per_chip = float(cost.get("flops", 0.0))  # per-device module

    hwspec = _HW[args.hw]
    t_compute = flops_per_chip / (hwspec["peak_flops"] * args.mfu)
    t_comm = comm_time_s(comm_ops, hwspec["ici_bw"],
                         default_group=default_group)
    rec = {
        "metric": f"{args.parallelism}_comm_fraction",
        "mesh": dict(mesh.shape),
        "hw": args.hw,
    }
    rec.update(extra)
    rec.update({
        "comm_bytes_per_step": sum(b for _, b, _ in comm_ops),
        "flops_per_chip_per_step": flops_per_chip,
        "mfu_assumed": args.mfu,
        "mfu_source": getattr(args, "mfu_source", "cli"),
        "comm_ms": round(t_comm * 1e3, 3),
        "compute_ms": round(t_compute * 1e3, 3),
        "comm_fraction_serial": round(t_comm / (t_comm + t_compute), 4),
        "efficiency_overlapped": round(
            t_compute / max(t_compute, t_comm), 4),
    })
    print(json.dumps(rec), flush=True)


def _ep_comm_fraction(args) -> int:
    """Expert-parallel MoE FFN fwd+bwd comm fraction (GShard all-to-all
    dispatch/combine) on an 8-way expert mesh, 2 experts/device."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.ops.collective import _smap
    from horovod_tpu.parallel import EXPERT_AXIS, expert_parallel_moe

    hvd.shutdown()
    hvd.init(axes={EXPERT_AXIS: 8})
    mesh = hvd.mesh()
    d, t, e_total = args.dim, args.seq_len, 16
    rng = np.random.RandomState(0)
    router = jnp.asarray(rng.randn(d, e_total).astype(np.float32) * 0.1)
    w1 = jnp.asarray(rng.randn(e_total, d, 4 * d).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(e_total, 4 * d, d).astype(np.float32) * 0.1)
    toks = jnp.asarray(rng.randn(t, d).astype(np.float32))

    def expert_fn(p, tok):
        a, b = p
        return jax.nn.relu(tok @ a) @ b

    def inner(r, a, b, tk):
        def loss_fn(rp, ap, bp):
            y, aux = expert_parallel_moe(
                rp, (ap, bp), tk, expert_fn, axis_name=EXPERT_AXIS,
                routing="top2")
            return jnp.mean(y * y) + 0.01 * aux

        return jax.grad(loss_fn, argnums=(0, 1, 2))(r, a, b)

    fn = jax.jit(_smap(
        inner, mesh,
        (P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P()),
        (P(), P(EXPERT_AXIS), P(EXPERT_AXIS)),
    ))
    _report_comm_fraction(
        args, fn.lower(router, w1, w2, toks).compile(), mesh,
        default_group=8,
        extra={"tokens": t, "dim": d, "experts": e_total, "routing": "top2"},
    )
    hvd.shutdown()
    return 0


def _pp_comm_fraction(args) -> int:
    """Pipeline-parallel TransformerLM train step (8-stage GPipe): the
    inter-stage activation handoffs lower to ``collective-permute``; report
    their wire cost against per-stage compute."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import TransformerLM
    from horovod_tpu.training import (
        make_transformer_pp_train_step, split_transformer_for_pp,
    )

    hvd.shutdown()
    S = 8
    hvd.init(axes={"pipe": S})
    mesh = hvd.mesh()
    depth = -(-max(args.depth, S) // S) * S  # round UP to a stage multiple
    if depth != args.depth:
        print(f"# pp: depth {args.depth} -> {depth} "
              f"(must be a multiple of {S} stages)", file=sys.stderr)
    model = TransformerLM(vocab=args.vocab, dim=args.dim, depth=depth,
                          heads=args.heads, max_len=args.seq_len)
    rng = np.random.RandomState(0)
    n_micro, mb, t = 2 * S, 1, args.seq_len
    tokens = rng.randint(0, args.vocab, (n_micro * mb, t)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(tokens[:1]))["params"]
    tx = optax.sgd(0.1)
    pp = split_transformer_for_pp(model, params, S)
    opt = {"embed": tx.init(pp["embed"]),
           "stages": jax.vmap(tx.init)(pp["stages"]),
           "head": tx.init(pp["head"])}
    sh = NamedSharding(mesh, P("pipe"))
    pp["stages"] = jax.tree_util.tree_map(
        lambda p: jax.device_put(p, sh), pp["stages"])
    step = make_transformer_pp_train_step(model, tx, donate=False)
    toks = jnp.asarray(tokens).reshape(n_micro, mb, t)
    compiled = step.lower(pp, opt, toks, jnp.asarray(
        np.roll(tokens, -1, 1)).reshape(n_micro, mb, t)).compile()
    _report_comm_fraction(
        args, compiled, mesh, default_group=S,
        extra={"stages": S, "n_micro": n_micro, "seq_len": t,
               "dim": args.dim, "depth": depth},
    )
    hvd.shutdown()
    return 0


def _hier_comm_fraction(args) -> int:
    """Hierarchical (cross×local) DP allreduce: compiled evidence + the
    two-fabric projection that quantifies WHY the toggle exists.

    Compiles the real DP train step on a ``{"cross": 2, "local": 4}`` mesh
    with ``HOROVOD_HIERARCHICAL_ALLREDUCE`` routing (reference rationale:
    ``nccl_operations.cc:162-354`` NCCLHierarchicalAllreduce — reduce
    inside the node at NVLink/ICI speed, cross the slow fabric once with
    1/local of the bytes, gather back inside). The distinct axis sizes let
    the HLO's ``replica_groups`` disambiguate which collective rides which
    fabric; the emitted record pins the compiled decomposition
    (local reduce-scatter + cross all-reduce on the 1/local shard + local
    all-gather) and prices each op on its own fabric.

    The multi-host projection then prices the SAME gradient volume on
    hosts×local configs with a shared per-host DCN NIC:

        flat ring (N = H·L chips, L ring links share the NIC):
            t = 2·B·(N−1)/N · L / dcn
        hierarchical:
            t = 2·B·(L−1)/L / ici  +  2·B·(H−1)/H / dcn

    — DCN traffic drops by ~L, which is the whole case for the
    hierarchical toggle (and for laying out shardings so collectives ride
    ICI, not DCN)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import models
    from horovod_tpu.ops import hierarchical
    from horovod_tpu.training import (
        init_model, make_shardmap_train_step, replicate, shard_batch,
    )

    hvd.shutdown()
    cross, local = 2, 4
    hvd.init(axes={"cross": cross, "local": local})
    hierarchical.set_hierarchical(True)  # before tracing (documented)
    try:
        cls = {"resnet50": "ResNet50", "resnet101": "ResNet101",
               "vgg16": "VGG16", "inception3": "InceptionV3"}[args.model]
        size = max(args.image_size, 75) if args.model == "inception3" else \
            args.image_size
        model = getattr(models, cls)(num_classes=1000, dtype=jnp.bfloat16)
        tx = optax.sgd(0.1)
        sample = jnp.zeros((1, size, size, 3), jnp.bfloat16)
        params, batch_stats = init_model(model, jax.random.PRNGKey(0),
                                         sample)
        n_params = sum(
            x.size for x in jax.tree_util.tree_leaves(params))
        step = make_shardmap_train_step(model, tx, donate=False)
        batch = cross * local * args.batch_per_chip
        x = shard_batch(np.zeros((batch, size, size, 3), np.float32))
        y = shard_batch(np.zeros((batch,), np.int64))
        compiled = step.lower(
            replicate(params), replicate(batch_stats),
            replicate(tx.init(params)), x, y).compile()
    finally:
        hierarchical.set_hierarchical(False)

    comm_ops = comm_ops_from_hlo(compiled.as_text())
    hwspec = _HW[args.hw]
    ici, dcn = hwspec["ici_bw"], args.dcn_gbps * 1e9
    # group size names the fabric: local-axis groups ride ICI (g==0, the
    # unparsed-"all" case, is conservatively priced as ICI too), cross-axis
    # groups ride the host NIC, which the local ranks share
    ops_ici = [o for o in comm_ops if o[2] in (local, 0)]
    ops_dcn = [o for o in comm_ops if o[2] not in (local, 0)]
    by_fabric = {"ici": sum(b for _, b, _ in ops_ici),
                 "dcn": sum(b for _, b, _ in ops_dcn)}
    t_comm = (comm_time_s(ops_ici, ici, default_group=local)
              + comm_time_s(ops_dcn, dcn / local, default_group=cross))

    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    flops_per_chip = float(cost.get("flops", 0.0))
    t_compute = flops_per_chip / (hwspec["peak_flops"] * args.mfu)

    grad_bytes = 4 * n_params
    proj = {}
    for hosts, loc in ((4, 8), (32, 8)):
        n = hosts * loc
        t_flat = 2.0 * grad_bytes * (n - 1) / n * loc / dcn
        t_hier = (2.0 * grad_bytes * (loc - 1) / loc / ici
                  + 2.0 * grad_bytes * (hosts - 1) / hosts / dcn)
        proj[f"{hosts}x{loc}"] = {
            "flat_ms": round(t_flat * 1e3, 3),
            "hier_ms": round(t_hier * 1e3, 3),
            "hier_speedup": round(t_flat / t_hier, 2),
            "hier_efficiency_overlapped": round(
                t_compute / max(t_compute, t_hier), 4),
            "flat_efficiency_overlapped": round(
                t_compute / max(t_compute, t_flat), 4),
        }

    print(json.dumps({
        "metric": "hier_comm_fraction",
        "mesh": {"cross": cross, "local": local},
        "hw": args.hw,
        "dcn_gbps_per_host": args.dcn_gbps,
        "params": n_params,
        "comm_bytes_by_fabric": by_fabric,
        "mfu_assumed": args.mfu,
        "mfu_source": getattr(args, "mfu_source", "cli"),
        "comm_ms_at_compiled_mesh": round(t_comm * 1e3, 3),
        "compute_ms": round(t_compute * 1e3, 3),
        "multi_host_projection": proj,
        "note": "hier_speedup is shape-independent (comm-only); the "
                "efficiency columns reflect the compiled --image-size/"
                "--batch-per-chip, which default small to keep the 1-core "
                "compile tractable — use the reference shape (224, 64) for "
                "absolute efficiency claims",
    }), flush=True)
    hvd.shutdown()
    return 0


def _resolve_mfu(artifacts: str = None) -> tuple:
    """Best MEASURED mfu_vs_peak banked by the round-long TPU window watcher
    (tools/tpu_window_watcher.py rung ``mfu``), else the 0.4 literature
    default. The fraction is an achieved-utilization estimate for the large
    bf16 matmul — transferable across TPU generations as a roofline input
    even when --hw differs from the chip that measured it (VERDICT r4: the
    projection's 0.4 assumption was itself unmeasured)."""
    import glob

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if artifacts:
        pats = [os.path.join(artifacts, "mfu_*.json")]
    else:
        # live watcher dir (gitignored) plus the committed evidence snapshot,
        # so a fresh checkout still gets the measured number
        pats = [os.path.join(repo, ".tpu_watch", "mfu_*.json"),
                os.path.join(repo, "docs", "evidence", "*", "mfu_*.json")]
    import time as _time

    sys.path.insert(0, os.path.join(_REPO_ROOT, "tools"))
    from tpu_window_watcher import FRESHNESS_S, artifact_ok

    best = None
    now = _time.time()
    for path in (p for pat in pats for p in glob.glob(pat)):
        try:
            # live-watcher artifacts from a previous round are stale; the
            # committed evidence snapshot is trusted at any age. The
            # acceptance policy itself (rc, value, hardware-not-CPU) is the
            # watcher's shared artifact_ok — same predicate bench.py's
            # merge applies, so the two cannot drift.
            if (".tpu_watch" in path
                    and now - os.path.getmtime(path) > FRESHNESS_S):
                continue
            with open(path) as f:
                data = json.load(f)
        except (ValueError, OSError):
            continue
        frac = data.get("mfu_vs_peak")
        if not frac or not artifact_ok(data):
            continue
        if best is None or frac > best[0]:
            best = (frac, f"measured:{os.path.basename(path)}"
                          f" ({data.get('device_kind', '?')})")
    if best is not None:
        return best
    return 0.4, "assumed-default"


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--parallelism", default="dp",
                   choices=["dp", "sp", "tp", "ep", "pp", "hier"],
                   help="dp: image-model DP allreduce roofline (multi-chip "
                        "projection); sp: ring-attention sequence-parallel "
                        "LM, comm-fraction at the compiled mesh; tp: "
                        "Megatron-style tensor-parallel LM, same; ep: "
                        "expert-parallel MoE FFN layer (all-to-all), same; "
                        "pp: 8-stage GPipe TransformerLM (ppermute), same")
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet101", "vgg16", "inception3"])
    p.add_argument("--dim", type=int, default=512)
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--vocab", type=int, default=8192)
    p.add_argument("--image-size", type=int, default=96,
                   help="compile-only: small images keep 1-core compile "
                        "tractable; conv flops scale but the comm bytes "
                        "(= gradient bytes) are size-independent")
    p.add_argument("--batch-per-chip", type=int, default=8)
    p.add_argument("--hw", default="tpu-v4", choices=sorted(_HW))
    p.add_argument("--dcn-gbps", type=float, default=25.0,
                   help="hier mode: per-host DCN NIC bandwidth in GB/s "
                        "(shared by the host's local chips); 25 GB/s ~ "
                        "200 Gbit ethernet")
    p.add_argument("--mfu", type=float, default=None,
                   help="achievable model-flops-utilization for t_compute "
                        "(peak*mfu); 100%% peak would overstate comm cost "
                        "~2-3x vs real conv/matmul utilization. Default: "
                        "the best measured mfu_vs_peak banked by "
                        "tools/tpu_window_watcher.py in --artifacts (a real "
                        "chip measurement), else 0.4")
    p.add_argument("--artifacts", default=None,
                   help="watcher artifact dir to read a MEASURED MFU from "
                        "(default: <repo>/.tpu_watch)")
    p.add_argument("--chips", type=int, nargs="+", default=[8, 32, 256])
    args = p.parse_args()

    args.mfu_source = "cli"
    if args.mfu is None:
        args.mfu, args.mfu_source = _resolve_mfu(args.artifacts)

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvd
    from horovod_tpu import models
    from horovod_tpu.training import (
        init_model, make_shardmap_train_step, replicate, shard_batch,
    )

    if args.parallelism == "ep":
        return _ep_comm_fraction(args)
    if args.parallelism == "hier":
        return _hier_comm_fraction(args)
    if args.parallelism == "pp":
        return _pp_comm_fraction(args)
    if args.parallelism != "dp":
        return _lm_comm_fraction(args)

    hvd.init()
    n_dev = hvd.size()
    cls = {"resnet50": "ResNet50", "resnet101": "ResNet101",
           "vgg16": "VGG16", "inception3": "InceptionV3"}[args.model]
    size = max(args.image_size, 75) if args.model == "inception3" else \
        args.image_size
    model = getattr(models, cls)(num_classes=1000, dtype=jnp.bfloat16)
    tx = optax.sgd(0.1)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, size, size, 3), jnp.bfloat16)
    params, batch_stats = init_model(model, rng, sample)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))

    step = make_shardmap_train_step(model, tx, donate=False)
    batch = n_dev * args.batch_per_chip
    x = shard_batch(np.zeros((batch, size, size, 3), np.float32))
    y = shard_batch(np.zeros((batch,), np.int64))
    pA, sA, oA = replicate(params), replicate(batch_stats), replicate(
        tx.init(params))

    lowered = step.lower(pA, sA, oA, x, y)
    compiled = lowered.compile()
    hlo = compiled.as_text()
    comm_bytes = comm_bytes_from_hlo(hlo)
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    # cost_analysis() runs on the SPMD-partitioned PER-DEVICE module (the
    # same one as_text() prints — its all-reduce shapes are full gradient
    # size), so its flops figure is already per chip. Verified empirically:
    # a [32,128]@[128,128] matmul sharded 4 ways reports 2*8*128*128.
    flops_per_chip = float(cost.get("flops", 0.0))

    hwspec = _HW[args.hw]
    t_compute = flops_per_chip / (hwspec["peak_flops"] * args.mfu)
    proj = {}
    for n in args.chips:
        t_comm = 2.0 * (n - 1) / n * comm_bytes / hwspec["ici_bw"]
        proj[str(n)] = {
            "efficiency_overlapped": round(
                t_compute / max(t_compute, t_comm), 4),
            "efficiency_serial": round(
                t_compute / (t_compute + t_comm), 4),
            "comm_ms": round(t_comm * 1e3, 3),
            "compute_ms": round(t_compute * 1e3, 3),
        }

    print(json.dumps({
        "metric": "dp_scaling_projection",
        "model": args.model,
        "hw": args.hw,
        "params": n_params,
        "comm_bytes_per_step": comm_bytes,
        "flops_per_chip_per_step": flops_per_chip,
        "mfu_assumed": args.mfu,
        "mfu_source": getattr(args, "mfu_source", "cli"),
        "batch_per_chip": args.batch_per_chip,
        "image_size": size,
        "projection": proj,
    }), flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
