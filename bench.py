#!/usr/bin/env python
"""Synthetic image-model benchmark — the rebuild's analog of reference
``examples/tensorflow2_synthetic_benchmark.py`` (ResNet-50, synthetic images,
img/s). ``--model`` also covers the reference scaling table's resnet101 /
inception3 / vgg16 (``docs/benchmarks.rst:10-14``). Prints ONE JSON line:

    {"metric": "resnet50_images_per_sec_per_chip", "value": ..., "unit":
     "img/s/chip", "vs_baseline": ...}

Baseline: the reference's only published absolute number, 103.6 img/s/GPU
(tf_cnn_benchmarks ResNet-101, bs 64/GPU, 16 Pascal P100 over 25GbE —
``docs/benchmarks.rst:26-42``; see BASELINE.md).
"""

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_S_PER_CHIP = 103.6



# name -> (models attr, default image size, has reference baseline).
# resnet101/inception3/vgg16 are the reference's scaling-table workloads
# (docs/benchmarks.rst:10-14); its only *absolute* number is the ResNet-type
# 103.6 img/s/GPU, so vs_baseline is null for the other families.
_MODELS = {
    "resnet50": ("ResNet50", 224, True),
    "resnet101": ("ResNet101", 224, True),
    "inception3": ("InceptionV3", 299, False),
    "vgg16": ("VGG16", 224, False),
}


def _emit_skip(reason: str, model: str = "resnet50") -> None:
    print(
        json.dumps(
            {
                "metric": f"{model}_images_per_sec_per_chip",
                "value": None,
                "unit": "img/s/chip",
                "vs_baseline": None,
                "skipped": reason,
            }
        ),
        flush=True,
    )


def _probe_backend(tries: int = 2, probe_timeout: int = 45) -> bool:
    """Health-check the default JAX backend in a throwaway subprocess.

    The axon-tunnel TPU in this environment can wedge so hard that even
    ``jax.devices()`` hangs; probing in a subprocess under a timeout keeps
    the wedge out of this process. Worst case is bounded well under two
    minutes (2 x 45 s + one short pause) so a wedged chip costs the driver
    a predictable slice of its window, not 7+ minutes.
    """
    code = "import jax; d = jax.devices(); print(len(d), d[0].device_kind)"
    for attempt in range(tries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=probe_timeout,
            )
            if r.returncode == 0 and r.stdout.strip():
                print(f"# backend probe ok: {r.stdout.strip()}", file=sys.stderr)
                return True
            print(
                f"# backend probe attempt {attempt + 1}/{tries} failed "
                f"(rc={r.returncode}): {r.stderr.strip().splitlines()[-1:] }",
                file=sys.stderr,
            )
        except subprocess.TimeoutExpired:
            print(
                f"# backend probe attempt {attempt + 1}/{tries} timed out "
                f"after {probe_timeout}s (wedged backend?)",
                file=sys.stderr,
            )
        if attempt < tries - 1:
            time.sleep(5)
    return False


def main():
    p = argparse.ArgumentParser()
    p.add_argument(
        "--model",
        choices=sorted(_MODELS),
        default="resnet50",
        help="benchmark workload; the reference's scaling table covers "
        "resnet101, inception3 and vgg16 (docs/benchmarks.rst:10-14)",
    )
    p.add_argument("--batch-size", type=int, default=128, help="per-chip batch")
    p.add_argument(
        "--image-size", type=int, default=None,
        help="default: 299 for inception3, else 224",
    )
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument(
        "--no-probe",
        action="store_true",
        help="skip the subprocess backend health-check (CI/CPU runs)",
    )
    p.add_argument(
        "--run-timeout",
        type=int,
        default=1200,
        help="hard wall-clock cap (s) on the measured child run",
    )
    p.add_argument(
        "--in-process",
        action="store_true",
        help=argparse.SUPPRESS,  # child marker: run the workload here
    )
    args = p.parse_args()
    if args.iters < 1 or args.batch_size < 1:
        p.error("--iters and --batch-size must be >= 1")
    if args.image_size is None:
        args.image_size = _MODELS[args.model][1]

    if args.in_process:
        return _run_benchmark(args)

    if not args.no_probe and not _probe_backend():
        _emit_skip("tpu-unavailable", args.model)
        return 0

    # The probe passing does NOT guarantee the run survives: the tunnel-TPU
    # in this environment has been observed to answer a probe and then wedge
    # inside the *next* process's backend init, blocked in an uninterruptible
    # C call — where an in-process SIGALRM handler never runs (the main
    # thread must re-enter the bytecode loop to deliver it; round-3 failure
    # mode). The only reliable watchdog is an external one: run the measured
    # workload in a child and enforce the timeout from here.
    # --in-process short-circuits before the probe, so the forwarded flags
    # (incl. --run-timeout) are inert in the child.
    cmd = [sys.executable, os.path.abspath(__file__), *sys.argv[1:],
           "--in-process", "--no-probe"]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True
    )
    try:
        stdout, stderr = proc.communicate(timeout=args.run_timeout)
    except subprocess.TimeoutExpired as e:
        # Emit the skip BEFORE reaping: a child wedged in an uninterruptible
        # device call can survive SIGKILL until the syscall returns, and the
        # driver needs its JSON line regardless.
        sys.stderr.write((e.stderr or b"").decode("utf-8", "replace")
                         if isinstance(e.stderr, bytes) else (e.stderr or ""))
        _emit_skip("tpu-wedged-during-run", args.model)
        proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return 0
    sys.stderr.write(stderr)
    result_line = next(
        (ln for ln in reversed(stdout.splitlines())
         if ln.startswith("{")), None
    )
    if proc.returncode != 0 or result_line is None:
        _emit_skip(f"benchmark-child-failed: rc={proc.returncode}", args.model)
        return 0
    print(result_line, flush=True)
    return 0


def _run_benchmark(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    import horovod_tpu.models as models
    from horovod_tpu.training import (
        init_model,
        make_jit_train_step,
        replicate,
        shard_batch,
    )

    try:
        hvd.init()
    except Exception as e:  # backend died between probe and init
        _emit_skip(f"tpu-unavailable: {type(e).__name__}", args.model)
        return 0
    n_chips = hvd.size()
    model = getattr(models, _MODELS[args.model][0])(num_classes=1000)
    from horovod_tpu.compression import Compression

    compression = Compression.fp16 if args.fp16_allreduce else Compression.none
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=compression
    )

    rng = jax.random.PRNGKey(0)
    global_batch = args.batch_size * n_chips
    sample = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)
    params, batch_stats = init_model(model, rng, sample)
    params = replicate(params)
    batch_stats = replicate(batch_stats)
    opt_state = replicate(tx.init(params))

    step = make_jit_train_step(model, tx)

    images_np = np.random.RandomState(0).rand(
        global_batch, args.image_size, args.image_size, 3
    ).astype(np.float32)
    labels_np = np.random.RandomState(1).randint(0, 1000, global_batch)
    images = shard_batch(images_np)
    labels = shard_batch(labels_np)

    # AOT-compile once and run the loop through the compiled executable: the
    # same compile serves execution and cost analysis (a separate
    # lower().compile() would not populate jit's dispatch cache and would
    # compile ResNet-50 twice)
    step_flops = None
    try:
        compiled = step.lower(
            params, batch_stats, opt_state, images, labels
        ).compile()
        step = compiled
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        step_flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        pass  # cost analysis is best-effort; MFU line is skipped without it

    for _ in range(args.warmup):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    jax.block_until_ready((params, loss))

    from horovod_tpu.profiler import timed_steps

    state = [params, batch_stats, opt_state]

    def run_one():
        state[0], state[1], state[2], loss = step(
            state[0], state[1], state[2], images, labels
        )
        return loss

    losses, dt = timed_steps(run_one, args.iters)
    assert all(np.isfinite(l) for l in losses), f"non-finite loss: {losses[-5:]}"

    img_per_sec = global_batch * args.iters / dt
    per_chip = img_per_sec / n_chips

    device_kind = jax.devices()[0].device_kind
    result = {
        "metric": f"{args.model}_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": (
            round(per_chip / BASELINE_IMG_S_PER_CHIP, 3)
            if _MODELS[args.model][2] else None
        ),
        "n_chips": n_chips,
        "device_kind": device_kind,
    }
    from horovod_tpu.profiler import device_peak_flops

    peak = device_peak_flops(device_kind)
    if step_flops is not None and peak is not None:
        achieved = step_flops * args.iters / dt
        result["mfu"] = round(achieved / (n_chips * peak), 4)
        result["model_tflops_per_step"] = round(step_flops / 1e12, 3)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
