#!/usr/bin/env python
"""Synthetic ResNet-50 benchmark — the rebuild's analog of reference
``examples/tensorflow2_synthetic_benchmark.py`` (ResNet-50, synthetic images,
img/s). Prints ONE JSON line:

    {"metric": "resnet50_images_per_sec_per_chip", "value": ..., "unit":
     "img/s/chip", "vs_baseline": ...}

Baseline: the reference's only published absolute number, 103.6 img/s/GPU
(tf_cnn_benchmarks ResNet-101, bs 64/GPU, 16 Pascal P100 over 25GbE —
``docs/benchmarks.rst:26-42``; see BASELINE.md).
"""

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import ResNet50
from horovod_tpu.training import (
    init_model,
    make_jit_train_step,
    replicate,
    shard_batch,
)

BASELINE_IMG_S_PER_CHIP = 103.6


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=128, help="per-chip batch")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()
    if args.iters < 1 or args.batch_size < 1:
        p.error("--iters and --batch-size must be >= 1")

    hvd.init()
    n_chips = hvd.size()
    model = ResNet50(num_classes=1000)
    from horovod_tpu.compression import Compression

    compression = Compression.fp16 if args.fp16_allreduce else Compression.none
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=compression
    )

    rng = jax.random.PRNGKey(0)
    global_batch = args.batch_size * n_chips
    sample = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)
    params, batch_stats = init_model(model, rng, sample)
    params = replicate(params)
    batch_stats = replicate(batch_stats)
    opt_state = replicate(tx.init(params))

    step = make_jit_train_step(model, tx)

    images_np = np.random.RandomState(0).rand(
        global_batch, args.image_size, args.image_size, 3
    ).astype(np.float32)
    labels_np = np.random.RandomState(1).randint(0, 1000, global_batch)
    images = shard_batch(images_np)
    labels = shard_batch(labels_np)

    for _ in range(args.warmup):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    jax.block_until_ready((params, loss))

    # fence with device->host reads of the loss: block_until_ready alone does
    # not reliably fence the dispatch chain on all runtimes, which inflated
    # throughput ~80x. Each loss depends on the previous step's params, so
    # fetching it transitively forces every step up to that point — reading
    # with a 2-step lag keeps the device pipeline full (steps overlap with the
    # host sync) while the final reads force the complete chain before the
    # clock stops.
    import collections

    losses = []
    in_flight = collections.deque()
    t0 = time.perf_counter()
    for _ in range(args.iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
        in_flight.append(loss)
        if len(in_flight) > 2:
            losses.append(float(in_flight.popleft()))
    while in_flight:
        losses.append(float(in_flight.popleft()))
    dt = time.perf_counter() - t0
    assert all(np.isfinite(l) for l in losses), f"non-finite loss: {losses[-5:]}"

    img_per_sec = global_batch * args.iters / dt
    per_chip = img_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": "resnet50_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "img/s/chip",
                "vs_baseline": round(per_chip / BASELINE_IMG_S_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
